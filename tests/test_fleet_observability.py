"""Fleet observability plane (ISSUE 10): cross-process merged tracing,
device/KV telemetry gauges, SLO attainment windows, the per-request
prefix/offload ledger, and the Prometheus format checker.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from dynamo_tpu.utils import counters, instance, tracing

from .helpers import hub_pair
from .test_engine import collect, greedy_request, make_engine
from .test_tracing import armed


def _non_meta(trace):
    return [e for e in trace["traceEvents"] if e["ph"] != "M"]


# ----------------------------------------------------- wire/ingest merge


def test_traceparent_roundtrip():
    tp = tracing.make_traceparent("req-with-dashes-42")
    rid, span = tracing.parse_traceparent(tp)
    assert rid == "req-with-dashes-42"
    assert span and len(span) == 16
    assert tracing.parse_traceparent("garbage") == (None, None)


def test_wire_ingest_merged_two_tracks():
    """Two-context merged-trace round trip: spans recorded under a
    'worker' process label ship via the wire form, a 'frontend' context
    ingests them, and ONE export renders both processes on distinct
    named tracks with the same request id and monotonic ts."""
    with armed():
        rid = "r-merge-1"
        # --- worker context: engine-ish spans
        tracing.set_process("worker-a")
        t0 = time.perf_counter()
        tracing.complete("prefill", t0, t0 + 0.001, track="engine.steps",
                         req=rid)
        tracing.instant("seq.first_token", req=rid)
        wire = tracing.wire_events(request_id=rid)
        assert wire["process"] == "worker-a"
        assert {w["name"] for w in wire["events"]} == {
            "prefill", "seq.first_token"
        }
        assert all("ts_unix_us" in w for w in wire["events"])

        # --- frontend context: clear local state, record the http span,
        # ingest the worker batch
        tracing.clear()
        tracing.set_process("frontend")
        t1 = time.perf_counter()
        tracing.complete("http.request", t1, t1 + 0.002, req=rid)
        n = tracing.ingest(wire["events"], process="worker-a")
        assert n == 2

        trace = tracing.export()
        evs = _non_meta(trace)
        # both processes present, distinct pids
        pids = {e["pid"] for e in evs}
        assert len(pids) == 2
        # consistent request id across processes
        assert all(e["args"]["request_id"] == rid for e in evs)
        # monotonic after the merge sort
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)
        # process_name metadata names both sides; the worker's named
        # track survives the hop
        procs = {
            e["args"]["name"]
            for e in trace["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"
        }
        assert {"frontend", "worker-a"} <= procs
        tracks = {
            e["args"]["name"]
            for e in trace["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert "engine.steps" in tracks
        # request filter keeps the merged view
        filtered = _non_meta(tracing.export(request_id=rid))
        assert len(filtered) == len(evs)
        assert _non_meta(tracing.export(request_id="other")) == []
    tracing.set_process(None)


def test_foreign_registries_bounded():
    """Weeks of worker churn (a fresh process label per restart) must
    not grow the foreign pid/track registries without bound."""
    with armed():
        for i in range(tracing._FOREIGN_PIDS_MAX + 50):
            tracing.ingest(
                [{"name": "x", "ph": "i",
                  "ts_unix_us": time.time() * 1e6, "track": "t"}],
                process=f"worker-{i}",
            )
        tracing.export()
        assert len(tracing._foreign_pids) <= tracing._FOREIGN_PIDS_MAX
        assert len(tracing._foreign_tracks) <= tracing._TRACKS_MAX
        # evicted processes dropped their track entries too
        assert all(
            k[0] in tracing._foreign_pids for k in tracing._foreign_tracks
        )


def test_ingest_drops_malformed():
    with armed():
        n = tracing.ingest(
            [{"name": "x"}, 7, {"name": "ok", "ph": "i",
                                "ts_unix_us": time.time() * 1e6}],
            process="w",
        )
        assert n == 1


async def test_span_shipper_aggregator_over_hub():
    """Full round trip over a real hub: a SpanShipper sink forwards
    worker spans to the trace subject, a TraceAggregator ingests them,
    and the merged export shows the foreign process."""
    from dynamo_tpu.runtime.trace_plane import SpanShipper, TraceAggregator

    async with hub_pair() as (_, client):
        with armed():
            tracing.set_process("worker-hub")
            agg = await TraceAggregator(client).start()
            shipper = SpanShipper(client, flush_interval_s=0.05).start()
            rid = "r-hub-1"
            with tracing.span("engine.step", req=rid, track="engine.steps"):
                pass
            tracing.instant("seq.admit", req=rid)
            for _ in range(100):
                if agg.ingested >= 2:
                    break
                await asyncio.sleep(0.02)
            assert agg.ingested >= 2
            await shipper.close()
            await agg.close()
            trace = tracing.export(request_id=rid)
            evs = _non_meta(trace)
            # events exist locally (pid 0) AND as ingested foreign
            # copies (pid > 0, counter-assigned) under the shipped label
            pids = {e["pid"] for e in evs}
            assert 0 in pids and len(pids) == 2, pids
            assert max(pids) > 0
            procs = {
                e["args"]["name"]
                for e in trace["traceEvents"]
                if e["ph"] == "M" and e["name"] == "process_name"
            }
            assert "worker-hub" in procs
        tracing.set_process(None)


async def test_ingress_binds_traceparent():
    """The data-plane Ingress must bind the caller's request id for the
    handler task and record the rpc.recv hop."""
    from dynamo_tpu.runtime.component import Ingress, pack_payload
    from dynamo_tpu.runtime.pipeline.context import Context

    seen = {}

    class StubEngine:
        async def generate(self, ctx):
            seen["rid"] = tracing.current_request()

            async def _g():
                yield {"ok": 1}

            return _g()

    with armed():
        tp = tracing.make_traceparent("req-ingress")
        ctx = Context(
            pack_payload({"x": 1}), request_id="req-ingress",
            metadata={"traceparent": tp},
        )
        stream = await Ingress(StubEngine())(ctx)
        [_ async for _ in stream]
        assert seen["rid"] == "req-ingress"
        evs = _non_meta(tracing.export())
        recv = [e for e in evs if e["name"] == "rpc.recv"]
        assert recv and recv[0]["args"]["request_id"] == "req-ingress"
        _, span = tracing.parse_traceparent(tp)
        assert recv[0]["args"]["parent_span"] == span


# ------------------------------------------------------- telemetry gauges


async def test_engine_telemetry_gauges_cpu():
    """KV pool gauges, slot occupancy, compile counters and the
    device/host split must render on the CPU backend (HBM gauges are
    absent there — memory_stats() returns None)."""
    engine = make_engine()
    tokens, _, _ = await collect(engine, greedy_request([5, 6, 7], max_tokens=3))
    assert len(tokens) == 3
    m = engine.metrics()
    assert m["kv_pages_used"] >= 0
    assert m["kv_pages_free"] > 0
    assert m["kv_pages_peak_used"] >= 1  # the serve allocated pages
    assert 0.0 <= m["kv_fragmentation"] <= 1.0
    assert 0.0 <= m["slot_occupancy"] <= 1.0
    # compile listener: the serve jitted at least one step family
    assert m["compile_events"] >= 1
    assert m["compile_time_s"] > 0
    assert m["step_device_s"] >= 0
    # pool accounting consistency: used + cached + free == usable pages
    assert (
        m["kv_pages_used"] + m["kv_pages_cached"] + m["kv_pages_free"]
        == m["kv_total_blocks"]
    )
    await engine.close()


async def test_compile_span_on_trace():
    with armed():
        engine = make_engine()
        await collect(engine, greedy_request([9, 8, 7, 6], max_tokens=2))
        evs = _non_meta(tracing.export())
        compiles = [e for e in evs if e["name"] == "engine.compile"]
        assert compiles, "no engine.compile spans recorded"
        assert all(e["ph"] == "X" and e["dur"] > 0 for e in compiles)
        await engine.close()


# -------------------------------------------------------------- SLO math


def test_slo_window_boundary_and_breaches():
    from dynamo_tpu.llm.http.metrics import SloTracker

    slo = SloTracker(
        {"default": {"ttft_s": 1.0}, "gold": {"ttft_s": 0.5}},
        window_s=10.0,
    )
    # zero-series at registration, idle attainment 1.0
    text = "\n".join(slo.render())
    assert 'slo_breaches_total{metric="ttft",tenant="default"} 0' in text
    assert 'slo_attainment{metric="ttft",tenant="default"} 1.0' in text

    # synthetic stamps stay in the monotonic domain: render() prunes
    # with the real clock, so offsets must be relative to it
    base = time.monotonic()
    # boundary: EXACTLY at target attains
    slo.observe({"tenant": "default", "ttft_s": 1.0}, now=base)
    assert slo.attained_fraction("default", "ttft", now=base) == 1.0
    # over target breaches
    slo.observe({"tenant": "default", "ttft_s": 1.0001}, now=base + 1)
    assert slo.attained_fraction("default", "ttft", now=base + 1) == 0.5
    # burn-rate counters are monotonic
    text = "\n".join(slo.render())
    assert 'slo_breaches_total{metric="ttft",tenant="default"} 1' in text
    assert 'slo_requests_total{metric="ttft",tenant="default"} 2' in text

    # unknown tenant rides the default target, aggregated under default
    slo.observe({"tenant": "mystery", "ttft_s": 5.0}, now=base + 2)
    assert slo.attained_fraction(
        "default", "ttft", now=base + 2
    ) == pytest.approx(1 / 3)
    # configured tenant keeps its own row and target (0.5s)
    slo.observe({"tenant": "gold", "ttft_s": 0.7}, now=base + 3)
    assert slo.attained_fraction("gold", "ttft", now=base + 3) == 0.0

    # rolling window: old samples age out -> idle window back to 1.0
    assert slo.attained_fraction("default", "ttft", now=base + 900) == 1.0


def test_slo_empty_spec_exempts_tenant():
    """An explicitly EMPTY tenant spec means exempt — it must not fall
    through to the default targets or mint undeclared series."""
    from dynamo_tpu.llm.http.metrics import SloTracker

    slo = SloTracker({"default": {"ttft_s": 1.0}, "internal": {}})
    base = time.monotonic()
    slo.observe({"tenant": "internal", "ttft_s": 99.0}, now=base)
    text = "\n".join(slo.render())
    assert 'tenant="internal"' not in text
    assert 'slo_requests_total{metric="ttft",tenant="default"} 0' in text


def test_slo_snapshot_rides_worker_stats():
    from dynamo_tpu.llm.http.metrics import SloTracker
    from dynamo_tpu.llm.kv_router.metrics_aggregator import (
        KvMetricsAggregator,
        ProcessedEndpoints,
    )
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.llm.kv_router.publisher import KvMetricsPublisher

    slo = SloTracker({"default": {"ttft_s": 1.0}})
    base = time.monotonic()
    slo.observe({"ttft_s": 0.2}, now=base)
    slo.observe({"ttft_s": 3.0}, now=base + 0.5)

    class Eng:
        def metrics(self):
            return {"kv_active_blocks": 3}

    pub = KvMetricsPublisher.for_engine(Eng(), slo=slo)
    stats = pub.stats_handler()
    assert stats["slo_attainment"]["default/ttft"] == 0.5
    # survives the wire round trip (from_dict keeps the field, older
    # senders without it default to {})
    fpm = ForwardPassMetrics.from_dict(stats)
    assert fpm.slo_attainment == {"default/ttft": 0.5}
    assert ForwardPassMetrics.from_dict({}).slo_attainment == {}

    # fleet fold: aggregator reports mean/min/workers per key
    snap = ProcessedEndpoints(endpoints={
        1: ForwardPassMetrics(slo_attainment={"default/ttft": 0.5}),
        2: ForwardPassMetrics(slo_attainment={"default/ttft": 1.0}),
        3: ForwardPassMetrics(),  # no tracker: doesn't vote
    })
    agg = KvMetricsAggregator.__new__(KvMetricsAggregator)
    agg.current = snap
    fleet = agg.attainment()
    assert fleet["default/ttft"] == {
        "mean": 0.75, "min": 0.5, "workers": 2
    }


# ------------------------------------------------- prefix/offload ledger


async def test_finish_summary_carries_prefix_ledger():
    engine = make_engine()
    summaries = []
    engine.subscribe_requests(summaries.append)
    prompt = list(range(2, 2 + 24))  # 3 full pages at page_size=8
    await collect(engine, greedy_request(prompt, max_tokens=2))
    await collect(engine, greedy_request(prompt, max_tokens=2))
    assert len(summaries) == 2
    cold, warm = summaries
    assert cold["prefix"]["reused_blocks"] == 0
    assert warm["prefix"]["reused_blocks"] >= 2  # repeat hits the cache
    assert warm["tenant"] == "default"
    await engine.close()


async def test_offload_ledger_restored_under_pressure():
    """Forced pressure: HBM evicted between serves, host tier populated
    -> the repeat's ledger must show restored blocks (restored > 0) and
    the gate stats must agree."""
    engine = make_engine(
        num_pages=12, host_kv_pages=32, offload_batch_pages=8,
        max_batch_size=2, prefill_chunk=16,
    )
    summaries = []
    engine.subscribe_requests(summaries.append)
    prompt = list(range(2, 2 + 24))
    await collect(engine, greedy_request(prompt, max_tokens=4))
    for _ in range(100):
        if len(engine.host_pool) >= 3:
            break
        engine._maybe_start_offload()
        await asyncio.sleep(0.05)
    assert len(engine.host_pool) >= 3
    # evict the HBM prefix entirely
    for i in range(4):
        filler = list(range(100 + 24 * i, 100 + 24 * (i + 1)))
        await collect(engine, greedy_request(filler, max_tokens=2))
    engine.allocator.clear_cache()

    await collect(engine, greedy_request(prompt, max_tokens=4))
    ledger = summaries[-1]["prefix"]
    assert ledger["restored_blocks"] > 0, ledger
    assert engine.offload_gate_stats["restored"] > 0
    assert engine.metrics()["offload_restored"] > 0
    await engine.close()


async def test_declined_gate_reason_in_ledger():
    engine = make_engine(
        num_pages=12, host_kv_pages=32, offload_batch_pages=8,
        max_batch_size=2, prefill_chunk=16, max_model_len=96,
    )
    summaries = []
    engine.subscribe_requests(summaries.append)
    prompt = list(range(40, 72))
    await collect(engine, greedy_request(prompt, max_tokens=2))
    for _ in range(100):
        if len(engine.host_pool) >= 3:
            break
        engine._maybe_start_offload()
        await asyncio.sleep(0.05)
    engine.allocator.clear_cache()
    # losing economy: the gate must decline and say why
    engine._ema_restore_bps = 1e3
    engine._ema_prefill_tps = 1e6
    await collect(engine, greedy_request(prompt, max_tokens=2))
    ledger = summaries[-1]["prefix"]
    if ledger["declined_blocks"]:  # tier population is best-effort
        assert ledger["gate_reason"] == "restore_slower_than_recompute"
        assert ledger["restored_blocks"] == 0
    await engine.close()


# ---------------------------------------------- satellites: labels, prom


def test_counters_declare_zero_series():
    from dynamo_tpu.utils.counters import PromCounters

    counters.reset()
    try:
        counters.declare("my_new_total")
        text = "\n".join(PromCounters().render())
        assert "dynamo_tpu_my_new_total 0.0" in text
        assert "# TYPE dynamo_tpu_my_new_total counter" in text
        counters.inc("my_new_total", 2)
        text = "\n".join(PromCounters().render())
        assert "dynamo_tpu_my_new_total 2.0" in text
    finally:
        counters.reset()


def test_http_counter_gauge_declare():
    from dynamo_tpu.llm.http.metrics import Counter, Gauge

    c = Counter("x_total", "t")
    c.declare(model="m")
    lines = list(c.render())
    assert 'x_total{model="m"} 0.0' in lines
    c.inc(model="m")
    lines = list(c.render())
    assert 'x_total{model="m"} 1.0' in lines
    g = Gauge("y", "t")
    g.declare(a="1")
    assert 'y{a="1"} 0.0' in list(g.render())


def test_worker_id_label_and_jsonl():
    import json as _json
    import logging

    from dynamo_tpu.llm.http.metrics import (
        EngineMetrics,
        ServiceMetrics,
    )
    from dynamo_tpu.utils.logging import JsonlFormatter

    instance.set_worker_id("w-test-1")
    try:
        sm = ServiceMetrics()

        class Stub:
            def subscribe_requests(self, cb):
                pass

            def metrics(self):
                return {"request_active_slots": 1}

        sm.extra.append(EngineMetrics(Stub(), worker_id="w-test-1"))
        text = sm.render()
        assert 'dynamo_tpu_instance_info{worker_id="w-test-1"} 1' in text
        assert (
            'dynamo_tpu_engine_request_active_slots'
            '{worker_id="w-test-1"} 1.0' in text
        )
        rec = logging.LogRecord("t", logging.INFO, "f", 1, "hello", (), None)
        out = _json.loads(JsonlFormatter().format(rec))
        assert out["worker_id"] == "w-test-1"
    finally:
        instance.set_worker_id(None)


def test_check_prom_validator():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "check_prom",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "check_prom.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    good = (
        "# TYPE a_total counter\na_total 0\n"
        "# TYPE h_seconds histogram\n"
        'h_seconds_bucket{le="1.0"} 0\nh_seconds_bucket{le="+Inf"} 0\n'
        "h_seconds_sum 0.0\nh_seconds_count 0\n"
    )
    assert mod.validate(good) == []
    # duplicate series
    assert mod.validate("# TYPE a counter\na 1\na 2\n")
    # duplicate TYPE line — even a consistent one — is what the real
    # Prometheus parser rejects
    assert mod.validate(
        "# TYPE a counter\na 1\n# TYPE a counter\n"
    )
    # sample without TYPE
    assert mod.validate("b_total 1\n")
    # declared family with no samples (zero-series rule)
    assert mod.validate("# TYPE c_total counter\n")
    # incomplete histogram
    assert mod.validate(
        "# TYPE h histogram\n" 'h_bucket{le="1.0"} 0\nh_count 0\n'
    )
    # the real exposition passes
    from dynamo_tpu.llm.http.metrics import ServiceMetrics
    from dynamo_tpu.utils.counters import PromCounters

    sm = ServiceMetrics()
    sm.extra.append(PromCounters())
    assert mod.validate(sm.render()) == []


def test_metrics_export_single_type_line_per_family():
    """The standalone exporter's per-worker loops must declare each
    family ONCE however many labeled series they emit (Prometheus
    rejects a scrape with a second TYPE line)."""
    from dynamo_tpu.llm.kv_router.metrics_aggregator import (
        KvMetricsAggregator,
        ProcessedEndpoints,
    )
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.metrics_export import MetricsExporter

    exp = MetricsExporter.__new__(MetricsExporter)
    exp.hit_events = exp.hit_tokens = exp.request_tokens = 0
    agg = KvMetricsAggregator.__new__(KvMetricsAggregator)
    agg.current = ProcessedEndpoints(endpoints={
        1: ForwardPassMetrics(
            slo_attainment={"default/ttft": 1.0, "default/itl": 0.5}
        ),
        2: ForwardPassMetrics(slo_attainment={"default/ttft": 0.8}),
    })
    exp.aggregator = agg
    # control-plane fields __init__ would set (this test bypasses it)
    exp.prefill_queue_depth = 3
    exp.planner_status = {"desired": {"backend": 2}, "adjustments": 1}
    text = exp.render()
    types = [ln for ln in text.splitlines() if ln.startswith("# TYPE")]
    assert len(types) == len(set(types)), types
    assert 'slo_attainment{worker_id="2"' in text
    assert "slo_attainment_fleet_min" in text


async def test_debug_trace_request_filter():
    from aiohttp.test_utils import TestClient, TestServer

    from dynamo_tpu.llm.engines import EchoEngineFull
    from dynamo_tpu.llm.http.service import HttpService

    with armed():
        svc = HttpService()
        svc.manager.add_chat_model("echo", EchoEngineFull())
        client = TestClient(TestServer(svc.app))
        await client.start_server()
        try:
            resp = await client.post(
                "/v1/chat/completions",
                json={"model": "echo",
                      "messages": [{"role": "user", "content": "hi"}]},
                headers={"x-request-id": "rid-filter-1"},
            )
            assert resp.status == 200
            await client.post(
                "/v1/chat/completions",
                json={"model": "echo",
                      "messages": [{"role": "user", "content": "yo"}]},
                headers={"x-request-id": "rid-filter-2"},
            )
            trace = await (await client.get(
                "/debug/trace", params={"request_id": "rid-filter-1"}
            )).json()
            evs = [e for e in trace["traceEvents"] if e["ph"] != "M"]
            assert evs, "filtered trace empty"
            assert all(
                e["args"].get("request_id") == "rid-filter-1" for e in evs
            )
        finally:
            await client.close()
