"""HTTP service tests: SSE round trips, aggregation, error paths, metrics.

Mirrors reference coverage in lib/llm/tests/http-service.rs (counting /
always-fail engines, full SSE round trip) using aiohttp's client.
"""

import contextlib
import json

import aiohttp

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.engines import AlwaysFailEngine, EchoEngineCore, EchoEngineFull
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.runtime.pipeline.engine import link

from .fixtures import tiny_model_dir


@contextlib.asynccontextmanager
async def http_service():
    svc = HttpService()
    card = ModelDeploymentCard.from_local_path(tiny_model_dir(), name="tiny")
    pipeline = link(OpenAIPreprocessor(card), Backend.from_card(card), EchoEngineCore())
    svc.manager.add_chat_model("tiny", pipeline)
    svc.manager.add_completion_model("tiny", pipeline)
    svc.manager.add_chat_model("echo", EchoEngineFull())
    svc.manager.add_chat_model("broken", AlwaysFailEngine())
    await svc.start("127.0.0.1", 0)
    async with aiohttp.ClientSession(f"http://127.0.0.1:{svc.port}") as session:
        try:
            yield svc, session
        finally:
            pass
    await svc.stop()


async def _read_sse(resp):
    """Parse an SSE body into (events, data_items, done_seen)."""
    events, items, done = [], [], False
    current_event = None
    async for raw_line in resp.content:
        line = raw_line.decode().rstrip("\n")
        if line.startswith("event: "):
            current_event = line[len("event: ") :]
        elif line.startswith("data: "):
            data = line[len("data: ") :]
            if data == "[DONE]":
                done = True
            elif current_event:
                events.append((current_event, json.loads(data)))
                current_event = None
            else:
                items.append(json.loads(data))
    return events, items, done


async def test_models_and_health():
    async with http_service() as (svc, session):
        r = await session.get("/v1/models")
        assert r.status == 200
        names = {m["id"] for m in (await r.json())["data"]}
        assert {"tiny", "echo", "broken"} <= names
        r = await session.get("/health")
        assert r.status == 200


async def test_chat_streaming_sse():
    async with http_service() as (svc, session):
        r = await session.post(
            "/v1/chat/completions",
            json={
                "model": "tiny",
                "messages": [{"role": "user", "content": "hello world"}],
                "stream": True,
                "dyn_ext": {"annotations": ["token_ids"]},
            },
        )
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        events, items, done = await _read_sse(r)
        assert done
        assert any(name == "token_ids" for name, _ in events)
        text = "".join(
            c["choices"][0]["delta"].get("content", "")
            for c in items
            if c.get("choices")
        )
        assert "hello world" in text
        finishes = [
            c["choices"][0].get("finish_reason") for c in items if c.get("choices")
        ]
        assert finishes[-1] is not None


async def test_chat_non_streaming():
    async with http_service() as (svc, session):
        r = await session.post(
            "/v1/chat/completions",
            json={
                "model": "tiny",
                "messages": [{"role": "user", "content": "pack my box"}],
            },
        )
        assert r.status == 200
        body = await r.json()
        assert body["object"] == "chat.completion"
        assert "pack my box" in body["choices"][0]["message"]["content"]
        assert body["usage"]["total_tokens"] > 0


async def test_completions_endpoint():
    async with http_service() as (svc, session):
        r = await session.post(
            "/v1/completions",
            json={"model": "tiny", "prompt": "five dozen liquor jugs"},
        )
        assert r.status == 200
        body = await r.json()
        assert body["object"] == "text_completion"
        assert "five dozen" in body["choices"][0]["text"]


async def test_completions_echo_and_n():
    """Legacy completions options: echo=True prefixes the prompt text;
    n=2 returns two indexed choices."""
    async with http_service() as (svc, session):
        r = await session.post(
            "/v1/completions",
            json={
                "model": "tiny",
                "prompt": "pack my box",
                "echo": True,
            },
        )
        assert r.status == 200
        body = await r.json()
        assert body["choices"][0]["text"].startswith("pack my box")

        r = await session.post(
            "/v1/completions",
            json={"model": "tiny", "prompt": "two choices", "n": 2},
        )
        assert r.status == 200
        body = await r.json()
        assert [c["index"] for c in body["choices"]] == [0, 1]
        assert all("two choices" in c["text"] for c in body["choices"])


async def test_error_paths():
    async with http_service() as (svc, session):
        r = await session.post(
            "/v1/chat/completions",
            json={"model": "nope", "messages": [{"role": "user", "content": "x"}]},
        )
        assert r.status == 404
        r = await session.post(
            "/v1/chat/completions", data=b"{not json", headers={"Content-Type": "application/json"}
        )
        assert r.status == 400
        r = await session.post("/v1/chat/completions", json={"model": "tiny"})
        assert r.status == 400  # missing messages
        r = await session.post(
            "/v1/chat/completions",
            json={"model": "broken", "messages": [{"role": "user", "content": "x"}]},
        )
        assert r.status == 502


def test_histogram_buckets_are_cumulative_once():
    """Regression: bucket counts must never exceed +Inf/_count."""
    from dynamo_tpu.llm.http.metrics import Histogram

    h = Histogram("t_seconds", "test", buckets=(0.1, 1.0, 10.0))
    h.observe(0.05)
    lines = list(h.render())
    counts = {
        line.split("le=")[1].split("}")[0].strip('"'): float(line.rsplit(" ", 1)[1])
        for line in lines
        if "_bucket" in line
    }
    assert counts == {"0.1": 1.0, "1.0": 1.0, "10.0": 1.0, "+Inf": 1.0}


async def test_content_parts_messages():
    """OpenAI content-part lists are flattened to text before templating."""
    async with http_service() as (svc, session):
        r = await session.post(
            "/v1/chat/completions",
            json={
                "model": "tiny",
                "messages": [
                    {
                        "role": "user",
                        "content": [{"type": "text", "text": "hello world"}],
                    }
                ],
            },
        )
        assert r.status == 200
        body = await r.json()
        assert "hello world" in body["choices"][0]["message"]["content"]
        assert "'type'" not in body["choices"][0]["message"]["content"]
        # unsupported part type → 400, not 502
        r = await session.post(
            "/v1/chat/completions",
            json={
                "model": "tiny",
                "messages": [
                    {"role": "user", "content": [{"type": "image_url", "image_url": {}}]}
                ],
            },
        )
        assert r.status == 400


async def test_metrics_exposed():
    async with http_service() as (svc, session):
        await session.post(
            "/v1/chat/completions",
            json={"model": "tiny", "messages": [{"role": "user", "content": "hi"}]},
        )
        r = await session.get("/metrics")
        text = await r.text()
        assert 'dynamo_tpu_http_service_requests_total{endpoint="chat",model="tiny",status="success"} 1' in text
        assert "dynamo_tpu_http_service_request_duration_seconds_bucket" in text


async def test_engine_metrics_render_through_extra():
    """ServiceMetrics.extra: one scrape covers service + engine (the
    run.py serving path appends an EngineMetrics per local engine)."""
    from dynamo_tpu.llm.http.metrics import EngineMetrics

    class StubEngine:
        def metrics(self):
            return {"request_active_slots": 3, "kv_total_blocks": 63}

    async with http_service() as (svc, session):
        svc.metrics.extra.append(EngineMetrics(StubEngine()))
        r = await session.get("/metrics")
        text = await r.text()
        assert "dynamo_tpu_engine_request_active_slots 3.0" in text
        assert "dynamo_tpu_engine_kv_total_blocks 63.0" in text
        # histograms render complete zero series before any traffic
        assert "dynamo_tpu_engine_ttft_seconds_count 0" in text
        assert 'dynamo_tpu_engine_itl_seconds_bucket{le="+Inf"} 0' in text


async def test_debug_trace_request_span():
    """/debug/trace returns Chrome trace-event JSON carrying the request
    span (x-request-id echoed end to end) for a completed completion."""
    from dynamo_tpu.utils import tracing

    tracing.enable()
    tracing.clear()
    try:
        async with http_service() as (svc, session):
            r = await session.post(
                "/v1/completions",
                json={"model": "tiny", "prompt": "hello world"},
                headers={"x-request-id": "trace-me-1"},
            )
            assert r.status == 200
            assert r.headers["X-Request-Id"] == "trace-me-1"
            # a request without the header gets a minted id echoed back
            r2 = await session.post(
                "/v1/completions",
                json={"model": "tiny", "prompt": "again", "stream": True},
            )
            assert r2.status == 200
            minted = r2.headers["X-Request-Id"]
            assert minted
            await _read_sse(r2)

            r = await session.get("/debug/trace")
            assert r.status == 200
            d = await r.json()
            evs = d["traceEvents"]
            ts = [e["ts"] for e in evs if e["ph"] != "M"]
            assert ts == sorted(ts)
            assert all(e["ph"] in ("X", "i", "M") for e in evs)
            spans = [
                e for e in evs
                if e["name"] == "http.request" and e["ph"] == "X"
            ]
            mine = [
                e for e in spans if e["args"].get("request_id") == "trace-me-1"
            ]
            assert mine and mine[0]["args"]["status"] == 200
            assert mine[0]["dur"] >= 0
            assert any(
                e["args"].get("request_id") == minted for e in spans
            )
            # the preprocessor span joined the same request id via the
            # handler's contextvar binding
            assert any(
                e["name"] == "preprocess"
                and e["args"].get("request_id") == "trace-me-1"
                for e in evs
            )
    finally:
        tracing.disable()
        tracing.clear()


# ------------------------------------------- deadlines & typed errors
# (fault-tolerance spine, docs/robustness.md: x-request-timeout rides
# Context metadata; DeadlineExceeded -> 429 + Retry-After; PoolExhausted
# -> 503 + Retry-After)


async def test_request_timeout_header_invalid_is_400():
    async with http_service() as (svc, session):
        r = await session.post(
            "/v1/chat/completions",
            json={"model": "echo", "messages": [{"role": "user", "content": "x"}]},
            headers={"x-request-timeout": "soon"},
        )
        assert r.status == 400
        assert "x-request-timeout" in (await r.json())["error"]["message"]


async def test_request_timeout_zero_sheds_429_with_retry_after():
    async with http_service() as (svc, session):
        r = await session.post(
            "/v1/chat/completions",
            json={"model": "echo", "messages": [{"role": "user", "content": "x"}]},
            headers={"x-request-timeout": "0"},
        )
        assert r.status == 429
        assert r.headers.get("Retry-After") == "1"
        assert (await r.json())["error"]["type"] == "rate_limit_error"


async def test_request_timeout_header_rides_context_metadata():
    from dynamo_tpu.runtime.pipeline.context import Context

    seen = {}

    class CapturingEngine:
        async def generate(self, ctx: Context):
            seen.update(ctx.metadata)

            async def _gen():
                yield {"id": "x", "choices": [], "object": "chat.completion.chunk"}

            return _gen()

    svc = HttpService()
    svc.manager.add_chat_model("cap", CapturingEngine())
    await svc.start("127.0.0.1", 0)
    try:
        import aiohttp
        import time as _time

        async with aiohttp.ClientSession(f"http://127.0.0.1:{svc.port}") as s:
            t0 = _time.time()
            r = await s.post(
                "/v1/chat/completions",
                json={"model": "cap", "messages": [{"role": "user", "content": "x"}]},
                headers={"x-request-timeout": "12.5"},
            )
            assert r.status == 200
        assert seen.get("timeout_s") == 12.5
        assert abs(seen["deadline"] - (t0 + 12.5)) < 5.0
    finally:
        await svc.stop()


async def test_typed_engine_errors_map_to_429_and_503():
    from dynamo_tpu.llm.protocols.common import (
        DeadlineExceededError,
        PoolExhaustedError,
    )

    class ShedEngine:
        async def generate(self, ctx):
            raise DeadlineExceededError("budget spent", retry_after_s=2)

    class FullEngine:
        async def generate(self, ctx):
            raise PoolExhaustedError("no pages", retry_after_s=3)

    svc = HttpService()
    svc.manager.add_chat_model("shed", ShedEngine())
    svc.manager.add_chat_model("full", FullEngine())
    await svc.start("127.0.0.1", 0)
    try:
        import aiohttp

        async with aiohttp.ClientSession(f"http://127.0.0.1:{svc.port}") as s:
            body = {"messages": [{"role": "user", "content": "x"}]}
            r = await s.post(
                "/v1/chat/completions", json={"model": "shed", **body}
            )
            assert r.status == 429
            assert r.headers.get("Retry-After") == "2"
            r = await s.post(
                "/v1/chat/completions", json={"model": "full", **body}
            )
            assert r.status == 503
            assert r.headers.get("Retry-After") == "3"
            assert (await r.json())["error"]["type"] == "server_error"
    finally:
        await svc.stop()


async def test_nonstreaming_queue_timeout_converts_to_429():
    """A zero-token all-`timeout` aggregate (deadline died in the
    admission queue) becomes a REAL 429 on the non-streaming path."""

    class QueueTimeoutEngine:
        async def generate(self, ctx):
            async def _gen():
                yield {
                    "id": "x", "object": "chat.completion.chunk",
                    "choices": [{
                        "index": 0, "delta": {}, "finish_reason": "timeout",
                    }],
                }

            return _gen()

    svc = HttpService()
    svc.manager.add_chat_model("q", QueueTimeoutEngine())
    await svc.start("127.0.0.1", 0)
    try:
        import aiohttp

        async with aiohttp.ClientSession(f"http://127.0.0.1:{svc.port}") as s:
            r = await s.post(
                "/v1/chat/completions",
                json={"model": "q", "messages": [{"role": "user", "content": "x"}]},
            )
            assert r.status == 429
            assert r.headers.get("Retry-After") == "1"
    finally:
        await svc.stop()


async def test_global_health_counters_render_via_extra():
    from dynamo_tpu.utils import counters
    from dynamo_tpu.utils.counters import PromCounters

    counters.reset()
    try:
        async with http_service() as (svc, session):
            svc.metrics.extra.append(PromCounters())
            counters.inc("hub_reconnects_total")
            r = await session.get("/metrics")
            text = await r.text()
            assert "dynamo_tpu_hub_reconnects_total 1.0" in text
            # known counters render 0 before first increment
            assert "dynamo_tpu_lease_expired_total 0.0" in text
            assert "dynamo_tpu_breaker_open_total 0.0" in text
    finally:
        counters.reset()
