"""Fleet control plane tests (docs/control.md): frontend admission
ladder, tenant-priority engine scheduling, disagg deadline clamp, and
the k8s controller's planner-status mirror."""

import asyncio
import contextlib
import json

import aiohttp

from dynamo_tpu.engine.scheduler import (
    pick_admission_index,
    pick_preemption_victim,
)
from dynamo_tpu.llm.engines import EchoEngineFull
from dynamo_tpu.llm.http.admission import (
    AdmissionConfig,
    AdmissionController,
    priorities_from_targets,
)
from dynamo_tpu.llm.http.service import HttpService

from .helpers import hub_server

# -------------------------------------------------------------- admission


def make_controller(queue=0.0, attain=None, **cfg_kw):
    sig = {"queue": queue, "attain": attain}
    cfg = AdmissionConfig(eval_interval_s=0.0, **cfg_kw)
    ctl = AdmissionController(
        priorities={"interactive": 10, "batch": 0, "default": 0},
        cfg=cfg,
        queue_depth_fn=lambda: sig["queue"],
        attainment_fn=lambda: sig["attain"],
    )
    return ctl, sig


def test_admission_ok_admits_everyone():
    ctl, _ = make_controller(queue=100.0, attain=None)  # no SLO data
    assert ctl.check("batch") is None
    ctl2, _ = make_controller(queue=0.0, attain=0.5)  # burn but no queue
    assert ctl2.check("batch") is None


def test_admission_overload_sheds_lowest_priority_with_429():
    ctl, _ = make_controller(queue=10.0, attain=0.5)
    shed = ctl.check("batch")
    assert shed is not None and shed.status == 429
    assert shed.retry_after_s >= 1
    # the configured interactive class rides through
    assert ctl.check("interactive") is None


def test_admission_critical_sheds_mid_priority_with_503():
    ctl, _ = make_controller(queue=20.0, attain=0.5)  # > 2x watermark
    shed = ctl.check("batch")
    assert shed is not None and shed.status == 503
    # the TOP configured class is never shed by this gate
    assert ctl.check("interactive") is None


def test_admission_recovers_when_signals_heal():
    ctl, sig = make_controller(queue=10.0, attain=0.5)
    assert ctl.check("batch") is not None
    sig["attain"] = 1.0
    assert ctl.check("batch") is None


def test_admission_without_priority_classes_is_inert():
    """No configured priority classes = nothing to discriminate by: the
    gate must admit everyone (shedding 100% of uniform-class traffic
    would deliver zero goodput), honoring check()'s top-class promise."""
    cfg = AdmissionConfig(eval_interval_s=0.0)
    ctl = AdmissionController(
        priorities={}, cfg=cfg,
        queue_depth_fn=lambda: 100.0, attainment_fn=lambda: 0.1,
    )
    assert ctl.check("anyone") is None


def test_admission_shed_counter_bounds_tenant_cardinality():
    """The x-tenant-id header is attacker-controlled: unconfigured
    tenants must fold into the "default" counter row (the SloTracker
    rule), not mint one Prometheus series per unique header."""
    ctl, _ = make_controller(queue=10.0, attain=0.5)
    for i in range(20):
        assert ctl.check(f"rando-{i}") is not None
    rows = {k for k in ctl.shed_total._values}
    assert rows == {(("level", "overload"), ("tenant", "default"))}, rows


def test_admission_broken_signal_fails_open():
    cfg = AdmissionConfig(eval_interval_s=0.0)

    def boom():
        raise RuntimeError("metrics backend down")

    ctl = AdmissionController(
        priorities={}, cfg=cfg, queue_depth_fn=boom, attainment_fn=boom
    )
    assert ctl.check("anyone") is None


def test_priorities_from_targets():
    targets = {
        "interactive": {"ttft_s": 0.5, "priority": 10},
        "batch": {"ttft_s": 30.0},
        "weird": {"priority": "nope"},
    }
    assert priorities_from_targets(targets) == {
        "interactive": 10, "batch": 0, "weird": 0,
    }


def test_priority_of_falls_through_to_default():
    ctl, _ = make_controller()
    ctl.priorities["default"] = 3
    assert ctl.priority_of("interactive") == 10
    assert ctl.priority_of("never-seen") == 3


@contextlib.asynccontextmanager
async def admission_service(ctl):
    svc = HttpService(admission=ctl)
    svc.manager.add_chat_model("echo", EchoEngineFull())
    await svc.start("127.0.0.1", 0)
    async with aiohttp.ClientSession(f"http://127.0.0.1:{svc.port}") as s:
        yield svc, s
    await svc.stop()


async def test_http_admission_gate_sheds_and_stamps_priority():
    """End to end through the HTTP frontend: under overload the batch
    tenant gets the typed 429 + Retry-After BEFORE any engine work, the
    interactive tenant is served with its priority class stamped into
    Context metadata, and the shed counter rides /metrics."""
    ctl, sig = make_controller(queue=10.0, attain=0.5)
    seen = {}

    async def spy_generate(ctx):
        seen["metadata"] = dict(ctx.metadata)

        async def s():
            yield {
                "id": "x", "object": "chat.completion.chunk", "model": "echo",
                "choices": [{"index": 0, "delta": {"content": "hi"},
                             "finish_reason": "stop"}],
            }

        return s()

    async with admission_service(ctl) as (svc, session):
        engine = svc.manager.get_chat("echo")
        engine.generate = spy_generate
        body = {"model": "echo", "messages": [{"role": "user", "content": "x"}]}
        r = await session.post(
            "/v1/chat/completions", json=body,
            headers={"x-tenant-id": "batch"},
        )
        assert r.status == 429
        assert r.headers.get("Retry-After") == "1"
        assert "metadata" not in seen  # shed BEFORE the engine
        r2 = await session.post(
            "/v1/chat/completions", json=body,
            headers={"x-tenant-id": "interactive"},
        )
        assert r2.status == 200
        assert seen["metadata"]["tenant"] == "interactive"
        assert seen["metadata"]["priority"] == 10
        scrape = await (await session.get("/metrics")).text()
        assert "admission_shed_total" in scrape
        assert 'tenant="batch"' in scrape
        # idle gate: once signals heal, everything admits again
        sig["attain"] = 1.0
        r3 = await session.post(
            "/v1/chat/completions", json=body,
            headers={"x-tenant-id": "batch"},
        )
        assert r3.status == 200


# ------------------------------------------------- engine priority policy


class _FakeSeq:
    def __init__(self, seq_id, priority=0):
        self.seq_id = seq_id
        self.priority = priority


def test_pick_admission_index_fifo_within_class():
    waiting = [_FakeSeq(1, 0), _FakeSeq(2, 0), _FakeSeq(3, 0)]
    assert pick_admission_index(waiting) == 0  # uniform = pure FIFO
    waiting = [_FakeSeq(1, 0), _FakeSeq(2, 5), _FakeSeq(3, 5)]
    assert pick_admission_index(waiting) == 1  # highest class, FIFO inside


def test_pick_preemption_victim_lowest_priority_most_recent():
    seqs = [_FakeSeq(1, 0), _FakeSeq(2, 0), _FakeSeq(3, 0)]
    assert pick_preemption_victim(seqs).seq_id == 3  # uniform = recency
    seqs = [_FakeSeq(1, 0), _FakeSeq(2, 0), _FakeSeq(3, 10)]
    # the newest seq is interactive: the newest BATCH one yields instead
    assert pick_preemption_victim(seqs).seq_id == 2


def _engine(**kw):
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models import config as cfgmod

    defaults = dict(
        model=cfgmod.get_config("tiny"),
        dtype="float32",
        page_size=8,
        num_pages=64,
        max_batch_size=4,
        max_model_len=128,
        prefill_chunk=32,
        seed=0,
    )
    defaults.update(kw)
    return JaxEngine(EngineConfig(**defaults))


def _pre(prompt, max_tokens=8):
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens),
        sampling_options=SamplingOptions(greedy=True),
    )


async def _collect(engine, pre, priority=None):
    from dynamo_tpu.runtime.pipeline.context import Context

    ctx = Context(pre.to_dict())
    if priority is not None:
        ctx.metadata["priority"] = priority
    frames = [f async for f in await engine.generate(ctx)]
    return [t for f in frames for t in f.get("token_ids") or []]


async def test_priority_admission_jumps_queue():
    """One slot, three queued requests: the high-priority one admits
    before the earlier-submitted batch ones (FIFO broken exactly where
    the priority class says so)."""
    engine = _engine(max_batch_size=1)
    try:
        hold_t = asyncio.create_task(_collect(engine, _pre([5, 6, 7], 6)))
        await asyncio.sleep(0.2)  # occupy the single slot
        order: list[str] = []

        async def tagged(tag, prompt, priority):
            toks = await _collect(engine, _pre(prompt, 3), priority)
            order.append(tag)
            return toks

        low_t = asyncio.create_task(tagged("low", [9, 10, 11], 0))
        await asyncio.sleep(0.05)  # low is queued first
        hi_t = asyncio.create_task(tagged("hi", [12, 13, 14], 10))
        await asyncio.gather(hold_t, low_t, hi_t)
        assert order == ["hi", "low"], order
    finally:
        await engine.close()


async def test_priority_idle_byte_identical():
    """Priority machinery on but no priorities in flight: greedy streams
    byte-identical to an engine with priority_scheduling forced off."""
    prompts = [[3, 4, 5], [7, 8, 9, 10], [11, 12]]
    on = _engine()
    off = _engine(priority_scheduling=False)
    try:
        got_on = await asyncio.gather(
            *(_collect(on, _pre(p, 6)) for p in prompts)
        )
        got_off = await asyncio.gather(
            *(_collect(off, _pre(p, 6)) for p in prompts)
        )
        assert got_on == got_off
        assert all(got_on)
    finally:
        await on.close()
        await off.close()


# ------------------------------------------------- disagg deadline clamp


async def test_disagg_remote_wait_sheds_at_deadline():
    """_generate_remote must clamp the remote-KV wait to the request
    deadline and shed with DeadlineExceededError instead of starting a
    doomed local prefill (ISSUE 11 satellite)."""
    import time

    import pytest

    from dynamo_tpu.llm.disagg import DisaggDecodeWorker, DisaggRouter
    from dynamo_tpu.llm.protocols.common import DeadlineExceededError
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.pipeline.context import Context

    async with hub_server() as server:
        drt = await DistributedRuntime.from_settings(
            hub_addr=f"127.0.0.1:{server.port}"
        )
        try:
            local_calls = []

            class _NeverEngine:
                page_size = 8

                class allocator:
                    @staticmethod
                    def peek_prefix_tokens(tokens):
                        return 0

                async def generate(self, ctx, _blocks=None):
                    local_calls.append(ctx)

                    async def s():
                        yield {}

                    return s()

            await drt.ensure_data_plane()
            worker = DisaggDecodeWorker(
                drt, _NeverEngine(), "ctrl", "backend", router=DisaggRouter()
            )
            pre = _pre(list(range(32)), 4)
            ctx = Context(pre.to_dict())
            ctx.metadata["deadline"] = time.time() + 0.3  # tight budget
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceededError):
                await worker._generate_remote(ctx, pre)
            assert time.monotonic() - t0 < 5.0  # clamped, not 120 s
            assert not local_calls  # no doomed local prefill
            assert worker.stats()["remote_timeouts"] == 1
            # an ALREADY-expired deadline sheds before even queueing
            ctx2 = Context(pre.to_dict())
            ctx2.metadata["deadline"] = time.time() - 1.0
            with pytest.raises(DeadlineExceededError):
                await worker._generate_remote(ctx2, pre)
        finally:
            await drt.shutdown()


# ------------------------- admission signals on non-kv ingress modes


async def test_non_kv_ingress_admission_gets_fleet_signals():
    """round_robin/random ingress previously ran the admission gate
    BLIND (no aggregator = no signals = always admit). With
    collect_stats the ModelWatcher starts a standalone stats aggregator
    per service, so queue depth + worst attainment reach the gate the
    same way the kv mode's router aggregator feeds it."""
    from dynamo_tpu.llm.engines import EchoEngineCore
    from dynamo_tpu.llm.http.discovery import ModelWatcher, register_llm
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.run import _bind_ingress_admission
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    from .fixtures import tiny_model_dir

    async with hub_server() as server:
        hub_addr = f"127.0.0.1:{server.port}"
        worker = await DistributedRuntime.from_settings(hub_addr=hub_addr)
        frontend = await DistributedRuntime.from_settings(hub_addr=hub_addr)
        svc = HttpService()
        watcher = ModelWatcher(
            frontend, svc.manager, router_mode="round_robin",
            collect_stats=True,
        )
        try:
            # worker stats reply: a burning, deeply-queued snapshot
            def stats_handler():
                return {
                    "num_requests_waiting": 40,
                    "request_active_slots": 4,
                    "slo_attainment": {"default/ttft": 0.5},
                }

            card = ModelDeploymentCard.from_local_path(
                tiny_model_dir(), name="tiny-echo"
            )
            await register_llm(
                worker, EchoEngineCore(), card,
                "dyn://demo.backend.generate", stats_handler=stats_handler,
            )
            await watcher.start()
            for _ in range(50):
                if svc.manager.get_chat("tiny-echo"):
                    break
                await asyncio.sleep(0.1)
            assert "tiny-echo" in [
                m for m in svc.manager.list_models()
            ]
            assert watcher.stats_aggregators, "no stats aggregator started"

            ctl = AdmissionController(
                priorities={"interactive": 10, "batch": 0, "default": 0},
                cfg=AdmissionConfig(
                    eval_interval_s=0.0, queue_high_watermark=8.0
                ),
            )
            _bind_ingress_admission(ctl, watcher)
            # the aggregator scraped at start(); signals must reach the
            # gate and trip the ladder (queue 40 > 2x8 => critical)
            for _ in range(50):
                if ctl.check("batch") is not None:
                    break
                await asyncio.sleep(0.1)
            shed = ctl.check("batch")
            assert shed is not None and shed.status == 503
            assert ctl.check("interactive") is None
        finally:
            await watcher.stop()
            await worker.shutdown()
            await frontend.shutdown()


# ------------------------------------------------- k8s planner mirror


async def test_k8s_controller_mirrors_planner_status():
    """CrdController watches the planner's hub status document and
    patches CR status with the desired-replica block (the operator path
    shows the same truth the planner actuated)."""
    from dynamo_tpu.llm.planner import planner_status_key
    from dynamo_tpu.runtime.hub.client import HubClient
    from dynamo_tpu.sdk.k8s_controller import CrdController, K8sApi
    from dynamo_tpu.sdk.operator import GRAPH_PREFIX

    patches = []

    class _FakeApi(K8sApi):
        def __init__(self):
            super().__init__("http://unused")

        async def patch_status(self, namespace, name, status):
            patches.append((namespace, name, status))

        async def close(self):
            pass

    async with hub_server() as server:
        hub_addr = f"127.0.0.1:{server.port}"
        ctl = CrdController(_FakeApi(), hub_addr)
        ctl._hub = await HubClient.connect(hub_addr)
        try:
            # a reconciled CR the mirror can patch
            ctl._applied[f"{GRAPH_PREFIX}demo.graph1"] = {"entry": "m:C"}
            mirror = asyncio.create_task(ctl._mirror_planner())
            await asyncio.sleep(0.1)
            status = {
                "namespace": "dynamo",
                "desired": {"backend": 3, "prefill": 1},
                "attainment": {"min": 0.97, "mean": 0.99, "target": 0.99},
                "last_decision": "burn",
                "adjustments": 7,
            }
            await ctl._hub.kv_put(
                planner_status_key("dynamo"), json.dumps(status).encode()
            )
            for _ in range(50):
                if patches:
                    break
                await asyncio.sleep(0.1)
            assert patches, "no CR status patch arrived"
            ns, name, st = patches[-1]
            assert (ns, name) == ("demo", "graph1")
            # keyed by the planner's dynamo namespace so multi-namespace
            # planners merge-patch their own subkey
            block = st["planner"]["dynamo"]
            assert block["desiredReplicas"] == {"backend": 3, "prefill": 1}
            assert block["lastDecision"] == "burn"
            mirror.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await mirror
            if ctl._planner_watch is not None:
                await ctl._planner_watch.cancel()
        finally:
            await ctl._hub.close()
