"""Self-generated test fixtures: a tiny BPE tokenizer + HF-style model dir.

Built programmatically (no network, no copied artifacts) once per session
under a cache dir. Mirrors the role of the reference's checked-in
sample-model dirs (reference: lib/llm/tests/data/sample-models/).
"""

from __future__ import annotations

import json
import os
import tempfile

_CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "pack my box with five dozen liquor jugs",
    "how vexingly quick daft zebras jump",
    "sphinx of black quartz judge my vow",
    "hello world this is a tiny test corpus for the tokenizer",
    "streaming tokens over the wire one at a time",
    "paged attention blocks live in high bandwidth memory",
    "the mesh has eight devices and two axes",
    "STOP right there and END the stream now",
    "unicode snowman ☃ and accents éàü for byte level coverage",
]

CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message['role'] }}|>\n{{ message['content'] }}<|eot|>\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)

_DIR = None


def tiny_model_dir() -> str:
    """Create (once) and return a tiny HF-style model dir."""
    global _DIR
    if _DIR is not None and os.path.exists(os.path.join(_DIR, "tokenizer.json")):
        return _DIR
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    path = os.path.join(tempfile.gettempdir(), "dynamo_tpu_tiny_model")
    os.makedirs(path, exist_ok=True)
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=512,
        special_tokens=["<|bos|>", "<|eos|>", "<|eot|>", "<|user|>", "<|assistant|>", "<|system|>"],
        show_progress=False,
    )
    tok.train_from_iterator(_CORPUS, trainer)
    tok.save(os.path.join(path, "tokenizer.json"))
    with open(os.path.join(path, "tokenizer_config.json"), "w") as f:
        json.dump(
            {
                "bos_token": "<|bos|>",
                "eos_token": "<|eos|>",
                "chat_template": CHAT_TEMPLATE,
            },
            f,
        )
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(
            {
                "architectures": ["LlamaForCausalLM"],
                "model_type": "llama",
                "max_position_embeddings": 2048,
                "hidden_size": 64,
                "intermediate_size": 128,
                "num_attention_heads": 4,
                "num_key_value_heads": 2,
                "num_hidden_layers": 2,
                "vocab_size": 512,
                "rms_norm_eps": 1e-5,
                "rope_theta": 10000.0,
            },
            f,
        )
    _DIR = path
    return path
