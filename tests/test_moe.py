"""Sparse MoE (mixtral-style) + expert parallelism: block oracle match,
sharded forward equivalence on the ep axis, engine e2e serving."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dynamo_tpu import compat
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import get_config
from dynamo_tpu.models.moe import expert_capacity, init_moe_params, moe_block
from dynamo_tpu.parallel import mesh as meshmod

CFG = get_config("tiny-moe").with_(dtype="float32")


def moe_oracle(lp, cfg, x):
    """Per-token loop: route to top-k experts, weighted SwiGLU sum —
    assumes capacity is never exceeded."""
    b, t, d = x.shape
    out = np.zeros((b, t, d), np.float32)
    router = np.asarray(lp["router"], np.float32)
    for bi in range(b):
        for ti in range(t):
            h = np.asarray(x[bi, ti], np.float32)
            logits = h @ router
            probs = np.exp(logits - logits.max())
            probs /= probs.sum()
            top = np.argsort(-probs)[: cfg.num_experts_per_tok]
            w = probs[top] / probs[top].sum()
            for wi, e in zip(w, top):
                gate = np.asarray(lp["we_gate"], np.float32)[e]
                up = np.asarray(lp["we_up"], np.float32)[e]
                down = np.asarray(lp["we_down"], np.float32)[e]
                g = h @ gate
                silu = g / (1 + np.exp(-g))
                out[bi, ti] += wi * ((silu * (h @ up)) @ down)
    return out


def test_moe_block_matches_oracle():
    key = jax.random.PRNGKey(0)
    lp = init_moe_params(CFG, key, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, CFG.hidden_size))
    got = np.asarray(moe_block(lp, CFG, x))
    ref = moe_oracle(lp, CFG, np.asarray(x))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_capacity_drops_overflow_deterministically():
    # force every token's top-1 to expert 0 via a huge router column; with
    # N tokens > cap, tokens at batch positions >= cap lose their expert-0
    # slot (GShard priority: earlier rows win) and keep ONLY their
    # second-choice expert's weighted contribution
    cfg = CFG.with_(expert_capacity_factor=0.1)
    lp = init_moe_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    lp["router"] = lp["router"].at[:, 0].set(100.0)
    n = 64
    cap = expert_capacity(cfg, n)
    assert cap < n
    x = jax.random.normal(jax.random.PRNGKey(2), (1, n, cfg.hidden_size))
    out = np.asarray(moe_block(lp, cfg, x))
    assert np.isfinite(out).all()

    # replicate the GShard priority exactly: slot-major (all first
    # choices, row order, then all second choices); an assignment past
    # `cap` in its expert contributes nothing
    router = np.asarray(lp["router"], np.float32)
    counters = {e: 0 for e in range(cfg.num_experts)}
    per_tok = []
    for ti in range(n):
        h = np.asarray(x[0, ti], np.float32)
        logits = h @ router
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        top = np.argsort(-probs)[:2]
        w = probs[top] / probs[top].sum()
        per_tok.append((h, top, w))
    assignments = [[None, None] for _ in range(n)]
    for slot in range(2):
        for ti in range(n):
            e = int(per_tok[ti][1][slot])
            kept = counters[e] < cap
            counters[e] += 1
            assignments[ti][slot] = kept
    dropped = [ti for ti in range(n) if not all(assignments[ti])]
    assert dropped, "test setup must overflow some expert"
    for ti in range(n):
        h, top, w = per_tok[ti]
        expected = np.zeros(cfg.hidden_size, np.float32)
        for slot in range(2):
            if not assignments[ti][slot]:
                continue
            e = int(top[slot])
            g = h @ np.asarray(lp["we_gate"], np.float32)[e]
            silu = g / (1 + np.exp(-g))
            expected += w[slot] * (
                (silu * (h @ np.asarray(lp["we_up"], np.float32)[e]))
                @ np.asarray(lp["we_down"], np.float32)[e]
            )
        np.testing.assert_allclose(out[0, ti], expected, rtol=2e-4, atol=2e-4)


def test_padding_rows_do_not_consume_capacity():
    """With a real_mask, pad rows ahead of real tokens must not evict
    them from their routed expert."""
    cfg = CFG.with_(expert_capacity_factor=0.1)
    lp = init_moe_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    lp["router"] = lp["router"].at[:, 0].set(100.0)
    n = 64
    cap = expert_capacity(cfg, n)
    x = jax.random.normal(jax.random.PRNGKey(2), (1, n, cfg.hidden_size))
    # first half pads: without the mask they'd eat expert-0 capacity
    mask = jnp.arange(n)[None, :] >= (n - cap)
    out = np.asarray(moe_block(lp, cfg, x, real_mask=mask))
    # all real tokens (the last cap rows) got their full two-expert sum
    ref = moe_oracle(lp, cfg, np.asarray(x))
    np.testing.assert_allclose(
        out[0, n - cap:], ref[0, n - cap:], rtol=2e-4, atol=2e-4
    )
    # pad rows contribute nothing
    np.testing.assert_allclose(out[0, : n - cap], 0.0, atol=1e-6)


def test_sharded_forward_matches_single_device():
    """Full tiny-moe forward on an ep=2 x tp=2 x dp=2 mesh must match the
    unsharded forward (GSPMD all-to-alls change nothing numerically)."""
    rng = np.random.RandomState(0)
    b, t, page = 2, 16, 8
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = rng.randint(1, CFG.vocab_size, (b, t)).astype(np.int32)
    positions = np.tile(np.arange(t, dtype=np.int32), (b, 1))
    wslots = np.concatenate(
        [np.arange(page * (1 + 4 * i), page * (1 + 4 * i) + t) for i in range(b)]
    ).astype(np.int32)
    smat = np.stack(
        [np.arange(page * (1 + 4 * i), page * (1 + 4 * i) + t) for i in range(b)]
    ).astype(np.int32)

    kv = llama.init_kv_cache(CFG, 256, dtype=jnp.float32)
    ref, _ = llama.forward(
        params, CFG, jnp.asarray(tokens), jnp.asarray(positions), kv,
        jnp.asarray(wslots), jnp.asarray(smat),
    )

    mc = meshmod.MeshConfig(ep=2, tp=2, dp=2)
    meshmod.validate_model_mesh(CFG, mc)
    mesh = meshmod.build_mesh(mc, jax.devices()[:8])
    sharded = meshmod.shard_params(params, CFG, mesh)
    kv2 = llama.init_kv_cache(CFG, 256, dtype=jnp.float32)
    with compat.set_mesh(mesh):
        got, _ = jax.jit(llama.forward, static_argnums=(1,))(
            sharded, CFG, jnp.asarray(tokens), jnp.asarray(positions), kv2,
            jnp.asarray(wslots), jnp.asarray(smat),
        )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_mesh_rejects_bad_ep():
    try:
        meshmod.validate_model_mesh(CFG, meshmod.MeshConfig(ep=3))
        raise AssertionError("expected ValueError")
    except ValueError as e:
        assert "num_experts" in str(e)


async def test_engine_serves_moe_model():
    from .test_engine import collect, greedy_request, make_engine

    engine = make_engine(model=CFG)
    prompt = [5, 17, 42, 9]
    tokens, finish, _ = await collect(engine, greedy_request(prompt, max_tokens=6))
    assert len(tokens) == 6 and finish == "length"
    # determinism across a fresh engine (routing is stable)
    engine2 = make_engine(model=CFG)
    tokens2, _, _ = await collect(engine2, greedy_request(prompt, max_tokens=6))
    assert tokens2 == tokens
    await engine.close()
    await engine2.close()


def test_mixtral_weight_loading(tmp_path):
    """HF mixtral-style safetensors (block_sparse_moe.*) load into the
    stacked [E, ...] expert params and produce the same forward as
    directly-constructed params."""
    import torch
    from safetensors.torch import save_file

    cfg = CFG.with_(num_layers=1)
    params = llama.init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    lp = params["layers"][0]
    sd = {
        "model.embed_tokens.weight": torch.from_numpy(
            np.asarray(params["embed"])
        ),
        "model.norm.weight": torch.from_numpy(np.asarray(params["final_norm"])),
        "model.layers.0.input_layernorm.weight": torch.from_numpy(
            np.asarray(lp["attn_norm"])
        ),
        "model.layers.0.post_attention_layernorm.weight": torch.from_numpy(
            np.asarray(lp["mlp_norm"])
        ),
        "model.layers.0.block_sparse_moe.gate.weight": torch.from_numpy(
            np.ascontiguousarray(np.asarray(lp["router"]).T)
        ),
    }
    for our, hf in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj"),
                    ("wo", "o_proj")):
        sd[f"model.layers.0.self_attn.{hf}.weight"] = torch.from_numpy(
            np.ascontiguousarray(np.asarray(lp[our]).T)
        )
    for our, hf in (("we_gate", "w1"), ("we_up", "w3"), ("we_down", "w2")):
        for e in range(cfg.num_experts):
            sd[f"model.layers.0.block_sparse_moe.experts.{e}.{hf}.weight"] = (
                torch.from_numpy(np.ascontiguousarray(np.asarray(lp[our][e]).T))
            )
    save_file(sd, str(tmp_path / "model.safetensors"))

    from dynamo_tpu.models.weights import load_params

    loaded = load_params(str(tmp_path), cfg, dtype=jnp.float32)
    for key in ("router", "we_gate", "we_up", "we_down"):
        np.testing.assert_allclose(
            np.asarray(loaded["layers"][0][key]), np.asarray(lp[key]),
            rtol=1e-6, atol=1e-6,
        )
