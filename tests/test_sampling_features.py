"""Sampling feature depth: logprobs, frequency/presence/repetition
penalties, per-request seeds — the SamplingOptions surface the reference
forwards into vLLM (reference: lib/llm/src/protocols/common.rs:248),
implemented natively in the jitted sampler (ops/sampling.py) and the
engine's decode scan."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import config as cfgmod
from dynamo_tpu.ops.sampling import apply_penalties, sample_tokens
from dynamo_tpu.runtime.pipeline.context import Context

CFG = cfgmod.get_config("tiny")


def make_engine(**kw) -> JaxEngine:
    defaults = dict(
        model=CFG,
        dtype="float32",
        page_size=8,
        num_pages=64,
        max_batch_size=4,
        max_model_len=128,
        prefill_chunk=32,
        seed=0,
    )
    defaults.update(kw)
    return JaxEngine(EngineConfig(**defaults))


async def collect(engine, pre):
    frames = [f async for f in await engine.generate(Context(pre.to_dict()))]
    tokens = [t for f in frames for t in f.get("token_ids") or []]
    return tokens, frames


def request(prompt, max_tokens=8, **so_kw):
    return PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(**so_kw),
    )


# ------------------------------------------------------------- unit level


def test_apply_penalties_math():
    logits = jnp.asarray([[2.0, -1.0, 0.5, 3.0]])
    counts = jnp.asarray([[2, 1, 0, 0]], jnp.int8)
    out = apply_penalties(
        logits, counts,
        freq_pen=jnp.asarray([0.5]),
        pres_pen=jnp.asarray([0.25]),
        rep_pen=jnp.asarray([2.0]),
    )
    # token 0: 2.0 - 0.5*2 - 0.25 = 0.75, seen & positive -> /2 = 0.375
    # token 1: -1.0 - 0.5 - 0.25 = -1.75, seen & negative -> *2 = -3.5
    # tokens 2,3: unseen, untouched
    np.testing.assert_allclose(
        np.asarray(out[0]), [0.375, -3.5, 0.5, 3.0], rtol=1e-6
    )


def test_sample_tokens_logprobs_greedy():
    logits = jnp.asarray([[0.0, 2.0, 1.0], [5.0, 0.0, 0.0]])
    ids, lps = sample_tokens(
        logits, jax.random.PRNGKey(0),
        jnp.zeros(2), jnp.zeros(2, jnp.int32), jnp.ones(2),
        all_greedy=True, return_logprobs=True,
    )
    assert list(np.asarray(ids)) == [1, 0]
    expect = jax.nn.log_softmax(logits, axis=-1)
    np.testing.assert_allclose(
        np.asarray(lps), [expect[0, 1], expect[1, 0]], rtol=1e-5
    )


def test_penalties_are_pre_logprob_only():
    """Reported logprobs come from the RAW distribution even when
    penalties reshape the sampling distribution."""
    logits = jnp.asarray([[3.0, 2.9, 0.0]])
    counts = jnp.zeros((1, 3), jnp.int8).at[0, 0].set(5)
    ids, lps = sample_tokens(
        logits, jax.random.PRNGKey(0),
        jnp.zeros(1), jnp.zeros(1, jnp.int32), jnp.ones(1),
        all_greedy=True, return_logprobs=True,
        counts=counts,
        freq_pen=jnp.asarray([10.0]), pres_pen=jnp.asarray([0.0]),
        rep_pen=jnp.asarray([1.0]),
    )
    assert int(ids[0]) == 1  # token 0 penalized away
    expect = float(jax.nn.log_softmax(logits, axis=-1)[0, 1])
    np.testing.assert_allclose(float(lps[0]), expect, rtol=1e-5)


# ----------------------------------------------------------- engine level


async def test_engine_logprobs_stream():
    engine = make_engine()
    tokens, frames = await collect(
        engine, request([5, 6, 7], max_tokens=5, greedy=True, logprobs=True)
    )
    assert len(tokens) == 5
    token_frames = [f for f in frames if f.get("token_ids")]
    lps = [f["log_probs"][0] for f in token_frames]
    assert all(isinstance(lp, float) and lp <= 0.0 for lp in lps)
    np.testing.assert_allclose(
        token_frames[-1]["cum_log_probs"], sum(lps), rtol=1e-5
    )
    # without the flag, frames stay lean
    _, frames2 = await collect(
        engine, request([5, 6, 7], max_tokens=3, greedy=True)
    )
    assert all(f.get("log_probs") is None for f in frames2)
    await engine.close()


async def test_engine_logprobs_match_manual_forward():
    from dynamo_tpu.models import llama

    engine = make_engine()
    prompt = [9, 10, 11, 12]
    tokens, frames = await collect(
        engine, request(prompt, max_tokens=3, greedy=True, logprobs=True)
    )
    lps = [f["log_probs"][0] for f in frames if f.get("token_ids")]

    # manual: same params, full-context forward per step
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    ctx = list(prompt)
    for tok, lp in zip(tokens, lps):
        kv = llama.init_kv_cache(CFG, 256, dtype=jnp.float32)
        t = len(ctx)
        smat = jnp.arange(8, 8 + t, dtype=jnp.int32)[None, :]
        hidden, _ = llama.forward(
            params, CFG,
            jnp.asarray([ctx], jnp.int32),
            jnp.arange(t, dtype=jnp.int32)[None, :],
            kv, smat.reshape(-1), smat,
        )
        lg = llama.logits(params, CFG, hidden[0, -1])
        want_tok = int(jnp.argmax(lg))
        want_lp = float(jax.nn.log_softmax(lg)[want_tok])
        assert tok == want_tok
        np.testing.assert_allclose(lp, want_lp, rtol=2e-2, atol=1e-3)
        ctx.append(tok)
    await engine.close()


async def test_engine_frequency_penalty_blocks_repeats():
    """A huge frequency penalty under greedy decoding makes every
    generated token distinct from the prompt and from each other."""
    engine = make_engine()
    prompt = [20, 21, 22, 23]
    tokens, _ = await collect(
        engine,
        request(prompt, max_tokens=10, greedy=True, frequency_penalty=100.0),
    )
    assert len(tokens) == 10
    seen = set(prompt)
    for t in tokens:
        assert t not in seen, f"token {t} repeated despite penalty"
        seen.add(t)
    # control: without penalties the tiny random model DOES repeat
    tokens2, _ = await collect(engine, request(prompt, max_tokens=10, greedy=True))
    assert len(set(tokens2) | set(prompt)) < len(tokens2) + len(prompt)
    await engine.close()


async def test_engine_per_request_seed_reproducible():
    engine = make_engine()
    so = dict(temperature=1.0, seed=1234)
    a, _ = await collect(engine, request([3, 4, 5], max_tokens=8, **so))
    b, _ = await collect(engine, request([3, 4, 5], max_tokens=8, **so))
    assert a == b, "same seed + prompt must reproduce"
    c, _ = await collect(
        engine, request([3, 4, 5], max_tokens=8, temperature=1.0, seed=999)
    )
    assert len(c) == 8  # different seed serves fine (and usually differs)
    await engine.close()


async def test_pipeline_chat_logprobs_and_n():
    """HTTP-shaped pipeline: logprobs ride the SSE chunks and fold into
    the aggregate; n=2 produces two indexed choices."""
    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import (
        ChatCompletionRequest,
        aggregate_chat_stream,
    )
    from dynamo_tpu.runtime.pipeline.engine import link

    from .fixtures import tiny_model_dir

    card = ModelDeploymentCard.from_local_path(tiny_model_dir(), name="tiny")
    engine = make_engine(
        model=CFG.with_(vocab_size=512), max_model_len=256, num_pages=128
    )
    pipeline = link(OpenAIPreprocessor(card), Backend.from_card(card), engine)

    # logprobs on a single greedy choice
    req = ChatCompletionRequest.from_body({
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello there"}],
        "max_tokens": 4,
        "logprobs": True,
        "dyn_ext": {"greed_sampling": True, "ignore_eos": True},
    })
    chunks = [c async for c in await pipeline.generate(Context(req))]
    entries = [
        e
        for c in chunks
        for ch in c.get("choices", [])
        if ch.get("logprobs")
        for e in ch["logprobs"]["content"]
    ]
    assert len(entries) == 4
    assert all(e["logprob"] <= 0.0 and isinstance(e["token"], str) for e in entries)

    async def _replay(items):
        for it in items:
            yield it

    full = await aggregate_chat_stream(_replay(chunks))
    assert len(full["choices"][0]["logprobs"]["content"]) == 4

    # n=2: two indexed choices, both finishing
    req2 = ChatCompletionRequest.from_body({
        "model": "tiny",
        "messages": [{"role": "user", "content": "fan out"}],
        "max_tokens": 3,
        "n": 2,
        "temperature": 1.0,
        "seed": 7,
        "dyn_ext": {"ignore_eos": True},
    })
    chunks2 = [c async for c in await pipeline.generate(Context(req2))]
    full2 = await aggregate_chat_stream(_replay(chunks2))
    assert [c["index"] for c in full2["choices"]] == [0, 1]
    assert all(c["finish_reason"] for c in full2["choices"])
    assert full2["usage"]["completion_tokens"] == 6
    await engine.close()


async def test_generate_after_close_raises():
    """A closed engine must refuse requests, not queue them forever."""
    import pytest

    engine = make_engine()
    tokens, _ = await collect(engine, request([3, 4], max_tokens=2, greedy=True))
    assert len(tokens) == 2
    await engine.close()
    with pytest.raises(RuntimeError, match="closed"):
        await engine.generate(
            Context(request([5, 6], max_tokens=2, greedy=True).to_dict())
        )


async def test_engine_top_logprobs():
    """top_logprobs: per position, the k best alternatives from the raw
    distribution — the sampled greedy token must lead the list."""
    engine = make_engine()
    _, frames = await collect(
        engine,
        request([5, 6, 7], max_tokens=4, greedy=True, logprobs=True,
                top_logprobs=3),
    )
    token_frames = [f for f in frames if f.get("token_ids")]
    assert len(token_frames) == 4
    for f in token_frames:
        alts = f["top_log_probs"][0]
        assert len(alts) == 3
        # alternatives sorted descending; greedy sampled token == argmax
        lps = [lp for _, lp in alts]
        assert lps == sorted(lps, reverse=True)
        assert alts[0][0] == f["token_ids"][0]
        np.testing.assert_allclose(alts[0][1], f["log_probs"][0], rtol=1e-5)
    await engine.close()


async def test_pipeline_chat_top_logprobs():
    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.pipeline.engine import link

    from .fixtures import tiny_model_dir

    card = ModelDeploymentCard.from_local_path(tiny_model_dir(), name="tiny")
    engine = make_engine(
        model=CFG.with_(vocab_size=512), max_model_len=256, num_pages=128
    )
    pipeline = link(OpenAIPreprocessor(card), Backend.from_card(card), engine)
    req = ChatCompletionRequest.from_body({
        "model": "tiny",
        "messages": [{"role": "user", "content": "alternatives"}],
        "max_tokens": 3,
        "logprobs": True,
        "top_logprobs": 2,
        "dyn_ext": {"greed_sampling": True, "ignore_eos": True},
    })
    chunks = [c async for c in await pipeline.generate(Context(req))]
    entries = [
        e
        for c in chunks
        for ch in c.get("choices", [])
        if ch.get("logprobs")
        for e in ch["logprobs"]["content"]
    ]
    assert len(entries) == 3
    for e in entries:
        assert len(e["top_logprobs"]) == 2
        assert all(
            isinstance(a["token"], str) and a["logprob"] <= 0.0
            for a in e["top_logprobs"]
        )
    await engine.close()


async def test_penalties_survive_preemption():
    """A penalized stream preempted mid-decode (pages exhausted) must,
    after re-admission, still see its full history in the count buffer —
    _count_prompt recounts prompt + generated-so-far from seq.tokens."""
    import asyncio

    engine = make_engine(
        num_pages=20,  # tight: concurrent streams force preemption
        max_batch_size=4,
        max_model_len=96,
        prefill_chunk=16,
        page_size=8,
    )
    prompts = [[10 + 7 * k, 11 + 7 * k, 12 + 7 * k] for k in range(6)]
    results = await asyncio.gather(*(
        collect(
            engine,
            request(p, max_tokens=8, greedy=True, frequency_penalty=100.0),
        )
        for p in prompts
    ))
    for (tokens, _), p in zip(results, prompts):
        assert len(tokens) == 8
        seen = set(p)
        for t in tokens:
            assert t not in seen, f"repeat {t} in {tokens} (prompt {p})"
            seen.add(t)
    await engine.close()


async def test_engine_penalty_and_plain_mix_in_batch():
    """Penalized and plain requests share one decode dispatch."""
    import asyncio

    engine = make_engine()
    r1 = collect(
        engine,
        request([30, 31], max_tokens=6, greedy=True, frequency_penalty=50.0),
    )
    r2 = collect(engine, request([40, 41], max_tokens=6, greedy=True))
    (t1, _), (t2, _) = await asyncio.gather(r1, r2)
    assert len(t1) == 6 and len(t2) == 6
    assert len(set(t1)) == 6  # penalized stream has no repeats
    await engine.close()


async def test_wide_and_negative_seeds_fold_and_reproduce():
    """OpenAI-style seeds outside int32 (2**40) and negative seeds must
    serve (no numpy OverflowError in the decode table build) and stay
    reproducible — admission folds them into [0, 2**31)
    (ADVICE r3: engine.py:1355 / scheduler.py:109)."""
    engine = make_engine()
    for seed in (2**40 + 17, -5):
        a, _ = await collect(
            engine, request([3, 4, 5], max_tokens=6, temperature=1.0, seed=seed)
        )
        b, _ = await collect(
            engine, request([3, 4, 5], max_tokens=6, temperature=1.0, seed=seed)
        )
        assert len(a) == 6 and a == b, f"seed {seed} not reproducible: {a} vs {b}"
    # a wide seed and its int32 fold are the SAME stream (documented fold)
    c, _ = await collect(
        engine,
        request([3, 4, 5], max_tokens=6, temperature=1.0,
                seed=(2**40 + 17) & 0x7FFFFFFF),
    )
    a, _ = await collect(
        engine, request([3, 4, 5], max_tokens=6, temperature=1.0, seed=2**40 + 17)
    )
    assert c == a
    await engine.close()


def test_delta_generator_role_per_choice():
    """n>1 chat streaming: every choice index gets `delta.role` on its
    first chunk, not just the first chunk overall (ADVICE r3)."""
    from dynamo_tpu.llm.protocols.openai import DeltaGenerator

    d = DeltaGenerator("m", kind="chat")
    c0 = d.chunk("a", index=0)
    c1 = d.chunk("b", index=1)
    c0b = d.chunk("c", index=0)
    assert c0["choices"][0]["delta"].get("role") == "assistant"
    assert c1["choices"][0]["delta"].get("role") == "assistant"
    assert "role" not in c0b["choices"][0]["delta"]


async def test_completion_aggregator_keeps_top_logprobs():
    """Non-streaming /v1/completions with logprobs=N must carry the top-N
    alternatives the streaming chunks emit (ADVICE r3: openai.py:346)."""
    from dynamo_tpu.llm.protocols.openai import aggregate_completion_stream

    async def _chunks():
        yield {
            "id": "x", "created": 1, "model": "m",
            "choices": [{
                "index": 0, "text": "hi",
                "logprobs": {
                    "tokens": ["hi"], "token_logprobs": [-0.1],
                    "top_logprobs": [{"hi": -0.1, "yo": -2.0}],
                },
            }],
        }
        yield {
            "id": "x", "created": 1, "model": "m",
            "choices": [{
                "index": 0, "text": "!", "finish_reason": "stop",
                "logprobs": {
                    "tokens": ["!"], "token_logprobs": [-0.2],
                    "top_logprobs": [{"!": -0.2}],
                },
            }],
        }

    full = await aggregate_completion_stream(_chunks())
    lp = full["choices"][0]["logprobs"]
    assert lp["tokens"] == ["hi", "!"]
    assert lp["top_logprobs"] == [{"hi": -0.1, "yo": -2.0}, {"!": -0.2}]


async def test_n_gt_1_stream_never_iterated_cancels_cleanly():
    """If the caller abandons an n>1 stream without iterating it, no
    engine streams were started — the engine drains to idle instead of
    generating until natural stop (ADVICE r3: preprocessor.py:318)."""
    import asyncio

    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.pipeline.engine import link

    from .fixtures import tiny_model_dir

    card = ModelDeploymentCard.from_local_path(tiny_model_dir(), name="tiny")
    engine = make_engine(
        model=CFG.with_(vocab_size=512), max_model_len=256, num_pages=128
    )
    pipeline = link(OpenAIPreprocessor(card), Backend.from_card(card), engine)
    req = ChatCompletionRequest.from_body({
        "model": "tiny",
        "messages": [{"role": "user", "content": "abandoned"}],
        "max_tokens": 64,
        "n": 3,
        "temperature": 1.0,
        "dyn_ext": {"ignore_eos": True},
    })
    stream = await pipeline.generate(Context(req))
    # never iterate `stream`; lazily-created pumps mean nothing started
    del stream
    await asyncio.sleep(0.05)
    m = engine.metrics()
    assert m["request_active_slots"] == 0 and m["num_requests_waiting"] == 0, (
        f"abandoned n>1 request left live sequences: {m}"
    )
    await engine.close()


async def test_n_gt_1_partial_fanout_failure_kills_admitted_siblings():
    """If fork k's admission fails mid-creation, the already-admitted
    forks 0..k-1 must have their contexts killed so the engine stops
    generating for them (r4 review finding)."""
    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.pipeline.engine import link

    from .fixtures import tiny_model_dir

    seen_ctxs = []

    class FlakyEngine:
        """Admits the first two forks, rejects the third."""

        async def generate(self, ctx):
            if len(seen_ctxs) >= 2:
                raise ValueError("admission rejected")
            seen_ctxs.append(ctx)

            async def _gen():
                yield {"token_ids": [1], "tokens": ["x"], "text": "x"}

            return _gen()

    card = ModelDeploymentCard.from_local_path(tiny_model_dir(), name="tiny")
    pipeline = link(OpenAIPreprocessor(card), Backend.from_card(card), FlakyEngine())
    req = ChatCompletionRequest.from_body({
        "model": "tiny",
        "messages": [{"role": "user", "content": "x"}],
        "max_tokens": 4,
        "n": 3,
        "temperature": 1.0,
    })
    stream = await pipeline.generate(Context(req))
    import pytest

    with pytest.raises(ValueError, match="admission rejected"):
        async for _ in stream:
            pass
    assert len(seen_ctxs) == 2
    assert all(c.is_stopped() for c in seen_ctxs), (
        "admitted sibling contexts must be killed on partial fan-out failure"
    )


# ------------------------------------------------- count-buffer saturation


def test_count_buffers_saturate_past_int8_range():
    """The penalty count buffers are int8: a token repeated more than 127
    times in one stream must SATURATE at 127, not wrap negative. A wrap
    flips `seen = cnt > 0` to False and turns every penalty into a
    REWARD for the most-repeated token — the exact failure a 200-repeat
    stream used to hit. Pins both accumulators (per-step bump_counts and
    the admission-time count_tokens prompt scatter) and the penalty
    direction at the saturated count."""
    from dynamo_tpu.ops.sampling import bump_counts, count_tokens

    B, V = 2, 32
    tok = 7
    counts = jnp.zeros((B, V), jnp.int8)
    tokens = jnp.asarray([tok, tok], jnp.int32)
    active = jnp.asarray([True, False])
    step = jax.jit(bump_counts)
    for _ in range(200):  # a 200-repeat stream
        counts = step(counts, tokens, active)
    out = np.asarray(counts)
    assert out[0, tok] == 127, f"wrapped: count={out[0, tok]}"
    assert out[1, tok] == 0  # inactive rows never bump
    assert (out >= 0).all()
    # admission path: a 200-token prompt of one repeated id saturates too
    counts2 = count_tokens(
        jnp.zeros((B, V), jnp.int8),
        jnp.asarray(0),
        jnp.full((200,), tok, jnp.int32),
    )
    assert np.asarray(counts2)[0, tok] == 127
    # and count_tokens ON TOP of an almost-saturated row stays pinned
    counts3 = count_tokens(
        counts, jnp.asarray(0), jnp.full((200,), tok, jnp.int32)
    )
    assert np.asarray(counts3)[0, tok] == 127
    # penalties at the saturated count still PENALIZE (never boost)
    logits = jnp.zeros((B, V))
    pen = apply_penalties(
        logits, counts,
        freq_pen=jnp.asarray([0.5, 0.5]),
        pres_pen=jnp.asarray([0.5, 0.5]),
        rep_pen=jnp.asarray([1.5, 1.5]),
    )
    assert float(pen[0, tok]) < float(logits[0, tok])
    assert float(pen[0, tok + 1]) == 0.0  # untouched elsewhere


async def test_engine_200_repeat_stream_counts_stay_saturated():
    """End-to-end regression for the int8 count wrap, driven past the
    wrap point: a stream whose token id 99 occurs 150 times (prompt
    scatter) plus decode steps. Saturated at 127, a huge frequency
    penalty keeps 99 suppressed for the whole stream; a wrapped count
    (-106) would flip the penalty into a +boost and greedy would emit 99
    every step. Also reads the count buffer back: no negative entries."""
    import asyncio

    engine = make_engine(max_model_len=256, max_batch_size=2)
    tok = 99
    prompt = [tok] * 150 + [20, 21]
    tokens, _ = await collect(
        engine,
        request(prompt, max_tokens=50, greedy=True, frequency_penalty=100.0),
    )
    assert len(tokens) == 50
    assert tok not in tokens, (
        "saturated count must keep penalizing token 99 — a wrapped int8 "
        "count would reward it instead"
    )
    # the count buffer itself: saturated at 127, nothing wrapped negative.
    # (one loop tick lets the pipelined in-flight step rebind the donated
    # buffer before we read it)
    counts = None
    for _ in range(100):
        try:
            counts = np.asarray(engine._counts)
            break
        except RuntimeError:
            await asyncio.sleep(0.02)
    assert counts is not None
    assert (counts >= 0).all(), "int8 count buffer wrapped negative"
    assert counts.max() == 127, f"expected saturation, got {counts.max()}"
    await engine.close()
