"""Block hashing tests (mirroring reference: lib/tokens/src/lib.rs tests)."""

from dynamo_tpu.llm.tokens import (
    ROOT_PARENT_HASH,
    TokenBlockSequence,
    chain_hash,
    compute_block_hashes,
    hash_block_tokens,
)


def test_block_chunking_and_partial():
    seq = TokenBlockSequence(range(10), block_size=4)
    assert len(seq.blocks) == 2
    assert seq.partial == [8, 9]
    assert seq.total_tokens == 10
    assert seq.all_tokens() == list(range(10))
    assert seq.blocks[0].tokens == (0, 1, 2, 3)
    assert seq.blocks[1].parent_sequence_hash == seq.blocks[0].sequence_hash
    assert seq.blocks[0].parent_sequence_hash == ROOT_PARENT_HASH


def test_incremental_extend_matches_bulk():
    bulk = TokenBlockSequence(range(20), block_size=4)
    inc = TokenBlockSequence([], block_size=4)
    for t in range(20):
        inc.extend([t])
    assert bulk.sequence_hashes() == inc.sequence_hashes()


def test_hash_determinism_and_chaining():
    h1 = hash_block_tokens([1, 2, 3, 4])
    assert h1 == hash_block_tokens([1, 2, 3, 4])
    assert h1 != hash_block_tokens([1, 2, 3, 5])
    assert chain_hash(0, h1) != chain_hash(h1, h1)


def test_same_block_different_prefix_different_sequence_hash():
    a = compute_block_hashes([1, 2, 3, 4, 9, 9, 9, 9], 4)
    b = compute_block_hashes([5, 6, 7, 8, 9, 9, 9, 9], 4)
    # same second block tokens, different parents → different sequence hashes
    assert a[1] != b[1]


def test_salt_changes_hashes():
    assert compute_block_hashes([1, 2, 3, 4], 4) != compute_block_hashes(
        [1, 2, 3, 4], 4, salt=b"model-v2"
    )


def test_shared_prefix_shares_hashes():
    a = compute_block_hashes(list(range(16)) + [100, 101, 102, 103], 4)
    b = compute_block_hashes(list(range(16)) + [200, 201, 202, 203], 4)
    assert a[:4] == b[:4]
    assert a[4] != b[4]
