"""Attribute the int8-KV decode step cost: full 1B model scan with the
decode kernel swapped for ablated variants (same dispatch machinery, so
deltas are trustworthy through the tunnel).

Run: python scripts/probe_decode_attrib.py [B]
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import dynamo_tpu.ops.pallas_attention as PA
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import get_config
from dynamo_tpu.ops.sampling import sample_tokens

B = int(sys.argv[1]) if len(sys.argv) > 1 else 256
CFG = get_config("llama-3.2-1b")
STEPS = 16
KV_LEN = 480
N = 6


def time_scan(b, kv_quant=True, ablate=None, knockout=False,
              nbuf=None, ppb=None, packed=False):
    pg = 128
    w_pages = -(-(KV_LEN + STEPS + pg) // pg)
    num_slots = (b * w_pages + 17) * pg
    tables = jnp.asarray(
        np.stack([np.arange(1 + i * w_pages, 1 + (i + 1) * w_pages)
                  for i in range(b)]), jnp.int32)
    temp = jnp.zeros((b,), jnp.float32)
    topk = jnp.zeros((b,), jnp.int32)
    topp = jnp.ones((b,), jnp.float32)

    def multi(params, kv, tokens, positions, key):
        def body(carry, _):
            tokens, positions, kv, key = carry
            key, sub = jax.random.split(key)
            wslots = (
                jnp.take_along_axis(
                    tables, (positions // pg)[:, None], axis=1
                )[:, 0] * pg + positions % pg
            ).astype(jnp.int32)
            spec = llama.AttnSpec.pallas_decode(
                tables, positions + 1, pg, write_pos=positions
            )
            hidden, kv = llama.forward(
                params, CFG, tokens[:, None], positions[:, None],
                kv, wslots, spec,
            )
            lg = llama.logits(params, CFG, hidden[:, 0])
            toks = sample_tokens(lg, sub, temp, topk, topp, all_greedy=True)
            return (toks, positions + 1, kv, key), toks

        (_, _, kv, _), out = jax.lax.scan(
            body, (tokens, positions, kv, key), None, length=STEPS)
        return out, kv

    from dynamo_tpu.ops.quant import quantize_params

    params = quantize_params(
        llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.bfloat16), CFG
    )
    kv = jax.device_put(llama.init_kv_cache(
        CFG, num_slots, dtype=jnp.bfloat16,
        kv_quant="int8" if kv_quant else None, page_size=pg,
    ))
    if packed:
        from dynamo_tpu.ops.quant import pack_kv_slots

        pk = jax.jit(pack_kv_slots)
        kv = llama.KVCache(
            k=tuple(pk(x) for x in kv.k),
            v=tuple(pk(x) for x in kv.v),
            ks=kv.ks, vs=kv.vs,
        )
    tokens = jnp.ones((b,), jnp.int32)
    positions = jnp.full((b,), KV_LEN, jnp.int32)
    key = jax.random.PRNGKey(0)

    real = PA.fused_paged_decode_attention
    try:
        if knockout:
            PA.fused_paged_decode_attention = (
                lambda q, nk, nv, kc, vc, tb, ln, wp, *a, **kw:
                (q, kc, vc, *a[:2]) if a and a[0] is not None
                else (q, kc, vc)
            )
        elif ablate or nbuf or ppb:
            kw = {}
            if ablate:
                kw["ablate"] = ablate
            if nbuf:
                kw["nbuf"] = nbuf
            if ppb:
                kw["pages_per_block"] = ppb
            PA.fused_paged_decode_attention = functools.partial(real, **kw)
        f = jax.jit(multi, donate_argnums=(1,))
        out, kv = f(params, kv, tokens, positions, key)
        _ = np.asarray(out[-1, :1])
        t0 = time.perf_counter()
        for _ in range(N):
            out, kv = f(params, kv, tokens, positions, key)
        _ = np.asarray(out[-1, :1])
        return (time.perf_counter() - t0) / N / STEPS
    finally:
        PA.fused_paged_decode_attention = real


def main():
    rows = [
        ("PACKED", dict(packed=True)),
        ("PACKED nbuf=16", dict(packed=True, nbuf=16)),
        ("PACKED ppb=8", dict(packed=True, ppb=8)),
        ("PACKED ppb=8 nbuf=16", dict(packed=True, ppb=8, nbuf=16)),
        ("PACKED ppb=2 nbuf=16", dict(packed=True, ppb=2, nbuf=16)),
        ("PACKED noscale_dma", dict(packed=True, ablate="noscale_dma")),
    ]
    for name, kw in rows:
        dt = time_scan(B, **kw)
        print(f"{name:24s} {dt * 1e3:7.3f} ms/step -> {B / dt:6.0f} tok/s",
              flush=True)


if __name__ == "__main__":
    main()
