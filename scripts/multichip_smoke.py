"""8-device multichip smoke: the sharded-path hang guard.

MULTICHIP_r05 hit rc=124 (timeout) and shipped silently because no
pre-merge gate exercised the sharded path (ROADMAP open item 1). This
script is that gate: it forces 8 virtual CPU devices, serves greedy
requests through a tp=8 engine with the step pipeline ON (the r05
suspect), and byte-compares against a single-device engine of the same
config — a sharded-path hang reads as the CI job's own timeout (red),
and a sharded-path divergence reads as the mismatch assert (red).

Four legs: gather tp=8 vs tp=1, the gather tp_overlap executor (cold +
warm waves), and the pallas+int8 packed-KV tp_overlap executor (cold +
warm waves, executor-attribution counters proving no GSPMD fallback) —
each byte-compared against its own tp=1 reference.

Run:  python scripts/multichip_smoke.py        (~2-6 min on CPU)
CI:   pre-merge.yml `multichip-smoke` job, wrapped in `timeout` so a
      hang can never eat the runner.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import asyncio  # noqa: E402

import jax  # noqa: E402

from dynamo_tpu.engine import EngineConfig, JaxEngine  # noqa: E402
from dynamo_tpu.llm.protocols.common import (  # noqa: E402
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import config as cfgmod  # noqa: E402
from dynamo_tpu.parallel.mesh import MeshConfig  # noqa: E402
from dynamo_tpu.runtime.pipeline.context import Context  # noqa: E402

# tiny widened to 8 kv heads so tp=8 actually shards the attention
CFG = cfgmod.get_config("tiny").with_(num_heads=8, num_kv_heads=8)

PROMPTS = (
    [5, 17, 42, 9, 88, 3],
    [11, 3, 7, 29, 31],
    [2, 44, 8, 19, 23, 61, 12],
)
MAX_TOKENS = 16

# live engines, so the timeout path can still read their phase stats —
# the MULTICHIP_r05 hang left a bare rc=124 with nothing to bisect on
_ENGINES: list = []


def make_engine(tp: int, tp_overlap: bool = False) -> JaxEngine:
    engine = JaxEngine(
        EngineConfig(
            model=CFG,
            dtype="float32",
            mesh=MeshConfig(tp=tp),
            page_size=8,
            num_pages=96,
            max_batch_size=4,
            max_model_len=128,
            prefill_chunk=32,
            # the r05 suspect paths stay ON: pipelined mixed steps over
            # the sharded mesh are exactly what a smoke must cover
            mixed_batching=True,
            step_pipeline=True,
            tp_overlap=tp_overlap,
            seed=0,
        )
    )
    _ENGINES.append(engine)
    return engine


def make_pallas_engine(tp: int, tp_overlap: bool = False) -> JaxEngine:
    """The production serving combination: pallas kernels (interpret on
    CPU) + int8 KV in int32-PACKED pools + mixed batching + the step
    pipeline. page_size=128 is the pallas+quantized floor (scale-page
    tokens live in lanes), so each sequence is one page."""
    engine = JaxEngine(
        EngineConfig(
            model=CFG,
            dtype="float32",
            mesh=MeshConfig(tp=tp),
            attn_backend="pallas",
            kv_quantization="int8",
            page_size=128,
            num_pages=8,
            max_batch_size=4,
            max_model_len=128,
            prefill_chunk=128,
            mixed_batching=True,
            step_pipeline=True,
            tp_overlap=tp_overlap,
            seed=0,
        )
    )
    _ENGINES.append(engine)
    return engine


def dump_timeout_artifact() -> str | None:
    """rc=124 evidence: trace ring + every engine's phase stats/metrics
    via the shared watchdog artifact writer (utils/artifacts.py)."""
    from dynamo_tpu.utils import artifacts, tracing

    payload = {
        "op": "multichip_smoke.timeout",
        "engines": [
            {
                "mesh_tp": e.config.mesh.tp,
                "phase_stats": e.phase_stats,
                "metrics": _safe_metrics(e),
            }
            for e in _ENGINES
        ],
        "trace": tracing.export(),
    }
    return artifacts.write_crash_artifact("multichip_smoke", payload)


def _safe_metrics(engine) -> dict:
    try:
        return engine.metrics()
    except Exception:  # noqa: BLE001 — artifact beats perfection here
        return {}


async def serve(engine) -> list[list[int]]:
    async def one(prompt):
        pre = PreprocessedRequest(
            token_ids=list(prompt),
            stop_conditions=StopConditions(max_tokens=MAX_TOKENS),
            sampling_options=SamplingOptions(greedy=True),
        )
        frames = [f async for f in await engine.generate(Context(pre.to_dict()))]
        assert frames[-1].get("finish_reason") == "length", frames[-1]
        return [t for f in frames for t in f.get("token_ids") or []]

    return list(await asyncio.gather(*(one(p) for p in PROMPTS)))


async def main() -> None:
    n_dev = jax.device_count()
    assert n_dev == 8, f"expected 8 virtual devices, got {n_dev}"

    ref_engine = make_engine(tp=1)
    want = await serve(ref_engine)
    await ref_engine.close()

    tp8 = make_engine(tp=8)
    got = await serve(tp8)
    # a second wave rides the prefix cache + warm compiled families —
    # the steady-state sharded path, not just the compile path
    got2 = await serve(tp8)
    await tp8.close()

    assert got == want, f"tp=8 diverged from tp=1:\n{got}\nvs\n{want}"
    assert got2 == want, f"tp=8 second wave diverged:\n{got2}\nvs\n{want}"

    # overlap leg: the latency-hiding manual-TP executor (ring
    # reduce-scatter residual stream, parallel/tp_overlap.py) must be
    # byte-identical too — a ring-scheduling regression reads red here
    ov8 = make_engine(tp=8, tp_overlap=True)
    assert ov8._tp_overlap_manual, "tp_overlap engine fell back to GSPMD"
    got_ov = await serve(ov8)
    got_ov2 = await serve(ov8)  # warm wave: steady-state ring path
    stats = ov8.phase_stats
    await ov8.close()
    assert got_ov == want, (
        f"tp=8 tp_overlap diverged from tp=1:\n{got_ov}\nvs\n{want}"
    )
    assert got_ov2 == want, (
        f"tp=8 tp_overlap second wave diverged:\n{got_ov2}\nvs\n{want}"
    )
    moved = sum(
        stats[k] for k in stats if k.endswith("_collective_bytes")
    )
    assert moved > 0, f"overlap engine recorded no collective bytes: {stats}"

    # pallas + packed int8 KV leg: the production backend combination
    # through the SAME overlap executor (the kernels' per-layer
    # shard_maps collapse into its single one) — mixed+pipeline stay on,
    # cold and warm waves, byte-compared against a tp=1 engine of the
    # same pallas+int8 config
    pal1 = make_pallas_engine(tp=1)
    want_pal = await serve(pal1)
    await pal1.close()

    pal8 = make_pallas_engine(tp=8, tp_overlap=True)
    assert pal8._tp_overlap_manual, (
        "pallas tp_overlap engine fell back to GSPMD: "
        f"{pal8.tp_overlap_refusal_reason!r}"
    )
    assert pal8._attn_pallas and pal8._kv_packed, "leg lost the pallas+packed path"
    got_pal = await serve(pal8)
    got_pal2 = await serve(pal8)  # warm wave
    pal_metrics = pal8.metrics()
    await pal8.close()
    assert got_pal == want_pal, (
        f"pallas+int8 tp=8 tp_overlap diverged from tp=1:\n{got_pal}\nvs\n{want_pal}"
    )
    assert got_pal2 == want_pal, (
        f"pallas+int8 tp=8 second wave diverged:\n{got_pal2}\nvs\n{want_pal}"
    )
    # executor attribution: every tp-collective dispatch went through the
    # overlap executor, none fell back to GSPMD
    served = pal_metrics["tp_overlap_dispatches"]
    fell_back = pal_metrics["gspmd_fallback_dispatches"]
    assert served > 0, f"no dispatch attributed to the overlap executor: {pal_metrics}"
    assert fell_back == 0, (
        f"{fell_back} dispatches fell back to GSPMD on the overlap engine"
    )

    print(
        f"multichip smoke ok: {n_dev} devices, tp=8, "
        f"{len(PROMPTS)} streams x {MAX_TOKENS} tokens byte-identical "
        "to tp=1 (mixed+pipeline on; overlap leg byte-identical, "
        f"{moved} exposed collective bytes attributed; pallas+int8 "
        f"packed-KV overlap leg byte-identical, {served} dispatches "
        "served by the executor, 0 GSPMD fallbacks)"
    )


if __name__ == "__main__":
    # arm the span recorder for the whole run: on the happy path it
    # costs a ring buffer; on the timeout path it is the step timeline
    # the crash artifact preserves
    from dynamo_tpu.utils import tracing as _tracing

    _tracing.enable()
    _tracing.set_process("multichip-smoke")
    try:
        asyncio.run(asyncio.wait_for(main(), timeout=840))
    except asyncio.TimeoutError:
        path = dump_timeout_artifact()
        print(
            "multichip smoke TIMED OUT (sharded-path hang); "
            f"crash artifact: {path or 'write failed'}",
            file=sys.stderr,
        )
        sys.exit(124)
