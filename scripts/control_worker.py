"""Chaos-controller scenario worker (driven by scripts/control_chaos.py).

A deliberately tiny "decode worker" with honest queueing dynamics and a
real SLO tracker, so the fleet control loop can be scored end to end
without a model:

- serves ``<ns>.<component>.generate`` on the hub data plane; each
  request takes a fixed service time on one of ``CHAOS_LANES`` parallel
  lanes, so saturation produces REAL queueing delay (latency degrades
  when capacity is lost, recovers when the planner adds a replica);
- a real `SloTracker` (short rolling window) judges every request
  against the TTFT target; its window fractions ride the stats replies
  exactly like a production worker's (ForwardPassMetrics.slo_attainment
  -> KvMetricsAggregator.attainment() — the planner's input);
- publishes its primary-lease id under the supervisor's drain key and
  runs the lease-validity gate (sdk/worker.py), so a planner scale-down
  drains it gracefully: revoke -> stop pulling -> finish in-flight ->
  exit 0;
- the designated victim (``CHAOS_VICTIM`` == --worker-id) consults the
  ``worker.die`` fault point per request: with
  ``DYN_FAULTS=worker.die.fail@N`` it hard-exits (rc 1) on its N-th
  request — the deterministic worker-death injection the scenario
  scores recovery from.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from dynamo_tpu.llm.http.metrics import SloTracker  # noqa: E402
from dynamo_tpu.utils import faults  # noqa: E402
from dynamo_tpu.utils.logging import configure_logging  # noqa: E402

NS = os.environ.get("CHAOS_NS", "chaos")
COMPONENT = os.environ.get("CHAOS_COMPONENT", "backend")
SERVICE_S = float(os.environ.get("CHAOS_SERVICE_S", "0.04"))
LANES = int(os.environ.get("CHAOS_LANES", "4"))
TTFT_TARGET_S = float(os.environ.get("CHAOS_TTFT_S", "0.2"))
SLO_WINDOW_S = float(os.environ.get("CHAOS_SLO_WINDOW_S", "3.0"))


async def amain(worker_id: int) -> None:
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.sdk.worker import lease_gate, publish_worker_lease

    # short lease TTL: a hard-killed victim must vanish from discovery
    # fast enough for the scenario's recovery clock to be about the
    # CONTROLLER, not the lease horizon
    drt = await DistributedRuntime.from_settings(  # DYN_HUB_ADDR
        lease_ttl=float(os.environ.get("CHAOS_LEASE_TTL", "1.5"))
    )
    stop = asyncio.Event()
    victim = worker_id == int(os.environ.get("CHAOS_VICTIM", "-1"))
    slo = SloTracker(
        {"default": {"ttft_s": TTFT_TARGET_S}}, window_s=SLO_WINDOW_S
    )
    lanes = asyncio.Semaphore(LANES)
    state = {"waiting": 0, "active": 0, "served": 0}

    class SimEngine:
        async def generate(self, ctx):
            if victim:
                # deterministic death: DYN_FAULTS=worker.die.fail@N
                try:
                    faults.fire("worker.die")
                except faults.FaultError:
                    os._exit(1)
            t0 = time.monotonic()

            async def stream():
                state["waiting"] += 1
                async with lanes:
                    state["waiting"] -= 1
                    state["active"] += 1
                    try:
                        await asyncio.sleep(SERVICE_S)
                    finally:
                        state["active"] -= 1
                lat = time.monotonic() - t0
                state["served"] += 1
                slo.observe({"tenant": "default", "ttft_s": lat})
                yield {"ttft_s": round(lat, 5), "worker": worker_id}

            return stream()

    ep = drt.namespace(NS).component(COMPONENT).endpoint("generate")
    served = await (
        ep.endpoint_builder()
        .engine(SimEngine())
        .stats_handler(
            lambda: {
                "request_active_slots": state["active"],
                "request_total_slots": LANES,
                "num_requests_waiting": state["waiting"],
                "gpu_cache_usage_perc": state["active"] / LANES,
                "slo_attainment": slo.snapshot(),
            }
        )
        .start()
    )

    # graceful-drain contract with the supervisor (docs/control.md)
    watcher_name = os.environ.get("DYN_WATCHER_NAME", "decoder")
    await publish_worker_lease(drt, watcher_name, worker_id)
    gate = asyncio.create_task(lease_gate(drt, stop, poll_s=0.25))

    await stop.wait()
    gate.cancel()
    # drain: deregister first (routers stop picking us), then let the
    # in-flight lanes finish before exiting 0
    await served.shutdown()
    while state["active"] or state["waiting"]:
        await asyncio.sleep(0.05)
    await drt.shutdown()


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--worker-id", type=int, default=0)
    args = p.parse_args()
    configure_logging()
    asyncio.run(amain(args.worker_id))


if __name__ == "__main__":
    main()
