"""Probe pltpu.bitcast int32<->int8 semantics on the real chip, and the
page-DMA rate of int32-packed vs int8 pools.

Establishes the ground truth for the packed int8-KV pool format
(docs/quantization.md "recovery plan"): int8 pages DMA ~20% slower per
byte than f32-class dtypes, so the pools store int32 [T/4, C] and the
kernels unpack with pltpu.bitcast. This probe pins down:
 1. forward bitcast row mapping (int32 [T, C] -> int8 [4T, C]);
 2. whether the reverse bitcast (int8 -> int32) compiles + inverts;
 3. measured DMA GB/s for int8 [page, kw] vs int32 [page/4, kw] pages.

Run: python scripts/probe_bitcast.py
"""

import functools
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def probe_forward():
    T, C = 8, 128
    rng = np.random.RandomState(0)
    x8 = rng.randint(-127, 128, size=(4 * T, C)).astype(np.int8)

    def kernel(x_ref, o_ref):
        o_ref[...] = pltpu.bitcast(x_ref[...], jnp.int8)

    # H1 pack: int32 row t packs int8 rows 4t..4t+3 little-endian
    h1 = (
        x8.reshape(T, 4, C).astype(np.uint8).astype(np.uint32)
    )
    h1 = (h1[:, 0] | (h1[:, 1] << 8) | (h1[:, 2] << 16) | (h1[:, 3] << 24)).view(
        np.int32
    )
    # H2 pack: int32 row t packs int8 rows t, T+t, 2T+t, 3T+t
    h2 = x8.reshape(4, T, C).astype(np.uint8).astype(np.uint32)
    h2 = (h2[0] | (h2[1] << 8) | (h2[2] << 16) | (h2[3] << 24)).view(np.int32)

    out_shape = jax.ShapeDtypeStruct((4 * T, C), jnp.int8)
    f = pl.pallas_call(kernel, out_shape=out_shape)
    for name, packed in (("H1-consecutive", h1), ("H2-strided", h2)):
        y = np.asarray(f(jnp.asarray(packed)))
        print(f"forward {name}: match={np.array_equal(y, x8)}")
        if not np.array_equal(y, x8):
            # where do rows land?
            for r in range(8):
                src = np.where((x8 == y[r]).all(axis=1))[0]
                print(f"  out row {r} == in row(s) {src}")
    return


def probe_reverse():
    T, C = 8, 128
    rng = np.random.RandomState(1)
    x8 = rng.randint(-127, 128, size=(4 * T, C)).astype(np.int8)

    def kernel(x_ref, o_ref):
        o_ref[...] = pltpu.bitcast(x_ref[...], jnp.int32)

    try:
        f = pl.pallas_call(
            kernel, out_shape=jax.ShapeDtypeStruct((T, C), jnp.int32)
        )
        y = np.asarray(f(jnp.asarray(x8)))
    except Exception as e:
        print(f"reverse bitcast FAILED: {type(e).__name__}: {e}")
        return
    h1 = x8.reshape(T, 4, C).astype(np.uint8).astype(np.uint32)
    h1 = (h1[:, 0] | (h1[:, 1] << 8) | (h1[:, 2] << 16) | (h1[:, 3] << 24)).view(
        np.int32
    )
    print(f"reverse bitcast: H1 match={np.array_equal(y, h1)}")


def probe_roundtrip_inject():
    """The decode write path: bitcast to int8, compute, inject a row in
    the int32 domain via shifts, write back."""
    T, C = 32, 128  # int8 rows
    rng = np.random.RandomState(2)
    x8 = rng.randint(-127, 128, size=(T, C)).astype(np.int8)
    new_row = rng.randint(-127, 128, size=(1, C)).astype(np.int8)
    off = 13  # inject at int8 row 13 -> int32 row 3, byte 1

    def kernel(x_ref, new_ref, off_ref, o_ref):
        x32 = x_ref[...]                      # [T//4, C] int32
        off = off_ref[0]
        b = jax.lax.rem(off, 4)
        r32 = jax.lax.div(off, 4)
        shift = b * 8
        nb = (new_ref[...].astype(jnp.int32) & 0xFF) << shift   # [1, C]
        mask = jnp.full_like(x32, 0xFF) << shift
        row = jax.lax.broadcasted_iota(jnp.int32, x32.shape, 0)
        x32 = jnp.where(row == r32, (x32 & ~mask) | nb, x32)
        o_ref[...] = x32

    packed = x8.reshape(T // 4, 4, C).astype(np.uint8).astype(np.uint32)
    packed = (
        packed[:, 0] | (packed[:, 1] << 8) | (packed[:, 2] << 16)
        | (packed[:, 3] << 24)
    ).view(np.int32)

    f = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((T // 4, C), jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )
    y = np.asarray(
        f(jnp.asarray(packed), jnp.asarray(new_row), jnp.asarray([off]))
    )
    want = x8.copy()
    want[off] = new_row[0]
    got = np.stack(
        [((y.view(np.uint32) >> (8 * j)) & 0xFF).astype(np.uint8) for j in range(4)],
        axis=1,
    ).reshape(T, C).view(np.int8) if False else None
    # decode H1: int32 row t -> int8 rows 4t..4t+3
    u = y.view(np.uint32)
    dec = np.zeros((T, C), np.uint8)
    for j in range(4):
        dec[j::4] = 0  # placeholder
    dec = np.empty((T // 4, 4, C), np.uint8)
    for j in range(4):
        dec[:, j] = (u >> (8 * j)) & 0xFF
    dec = dec.reshape(T, C).view(np.int8)
    print(f"inject-in-int32-domain: match={np.array_equal(dec, want)}")


def bench_dma(dtype, page, kw, n_pages=8192, nbuf=8, iters=3, reps=8):
    total_pages = 16384
    pool = jnp.zeros((total_pages, page, kw), dtype)
    rng = np.random.RandomState(0)
    # DISTINCT tables per chained rep: identical pallas calls inside the
    # timing scan would be CSE'd into one dispatch (measured: 12 ms wall
    # for 1 GB and for 4 GB alike — the tunnel artifact, not the DMA)
    tables = jnp.asarray(
        np.stack([rng.permutation(total_pages)[:n_pages] for _ in range(reps)]),
        jnp.int32,
    )

    def kernel(tables_ref, pages_hbm, out_ref, bufs, sems):
        for j in range(nbuf):
            pltpu.make_async_copy(
                pages_hbm.at[tables_ref[j]], bufs.at[j], sems.at[j]
            ).start()

        def body(i, acc):
            slot = jax.lax.rem(i, nbuf)
            pltpu.make_async_copy(
                pages_hbm.at[0], bufs.at[slot], sems.at[slot]
            ).wait()
            acc = acc + jnp.sum(bufs[slot, 0].astype(jnp.float32)) * 0.0
            nxt = i + nbuf

            @pl.when(nxt < n_pages)
            def _():
                pltpu.make_async_copy(
                    pages_hbm.at[tables_ref[nxt]], bufs.at[slot], sems.at[slot]
                ).start()

            return acc

        acc = jax.lax.fori_loop(0, n_pages, body, 0.0)
        out_ref[0, 0] = acc

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        scratch_shapes=[
            pltpu.VMEM((nbuf, page, kw), dtype),
            pltpu.SemaphoreType.DMA((nbuf,)),
        ],
    )
    bench = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
    )

    # chain N reps inside one jit (axon timing methodology)
    @jax.jit
    def run(t, p):
        def step(carry, ti):
            o = bench(ti, p)
            return carry + o[0, 0], None

        acc, _ = jax.lax.scan(step, 0.0, t)
        return acc

    _ = np.asarray(run(tables, pool))  # warmup/compile
    best = None
    for _ in range(iters):
        t0 = time.perf_counter()
        _ = np.asarray(run(tables, pool))
        dt = (time.perf_counter() - t0) / reps
        best = dt if best is None else min(best, dt)
    nbytes = n_pages * page * kw * np.dtype(dtype).itemsize
    gbs = nbytes / best / 1e9
    print(
        f"DMA {np.dtype(dtype).name:8s} page=[{page},{kw}] "
        f"{nbytes / 1e6:.0f} MB in {best * 1e3:.2f} ms -> {gbs:.0f} GB/s"
    )
    return gbs


def main():
    print(f"devices: {jax.devices()}")
    probe_forward()
    probe_reverse()
    probe_roundtrip_inject()
    # 8B-class dims: kw=1024, page=128 int8 -> packed [32, 1024] int32
    g8 = bench_dma(jnp.int8, 128, 1024)
    g32 = bench_dma(jnp.int32, 32, 1024)
    gbf = bench_dma(jnp.bfloat16, 64, 1024)  # same 128 KB/page in bf16
    print(f"int32 vs int8 speedup: {g32 / g8:.3f}x ; bf16 ref {gbf:.0f} GB/s")


if __name__ == "__main__":
    main()
