"""Prefill-step cost attribution: full 1B chunked-prefill forward in a
scan, with the flash-prefill attention knocked out to isolate its share.
Used to evaluate prefill-kernel changes (the 8B bench headline is ~85%
prefill wall at ISL512/OSL64).

Run: python scripts/probe_prefill_attrib.py [B] [T]
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import dynamo_tpu.ops.pallas_prefill as PF
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import get_config

B = int(sys.argv[1]) if len(sys.argv) > 1 else 16
T = int(sys.argv[2]) if len(sys.argv) > 2 else 512
CFG = get_config(os.environ.get("MODEL", "llama-3.2-1b"))
STEPS = int(os.environ.get("STEPS", "8"))
PG = 128
N = int(os.environ.get("N", "4"))


def time_scan(knockout=False, kv_quant=True, packed=True, ppb=None, t_tile=None):
    w = T // PG
    num_pages = B * w + 17
    num_slots = num_pages * PG
    tables = jnp.asarray(
        np.stack([np.arange(1 + i * w, 1 + (i + 1) * w) for i in range(B)]),
        jnp.int32,
    )
    # layerwise quantize during init: 8B-class bf16 whole-tree would OOM
    params = llama.init_params(
        CFG, jax.random.PRNGKey(0), dtype=jnp.bfloat16, quantize=True
    )
    kv = jax.device_put(llama.init_kv_cache(
        CFG, num_slots, dtype=jnp.bfloat16,
        kv_quant="int8" if kv_quant else None, page_size=PG, packed=packed,
    ))
    tokens = jnp.ones((B, T), jnp.int32)
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32), (B, 1))
    wslots = (
        tables[:, :, None] * PG
        + jnp.arange(PG, dtype=jnp.int32)[None, None, :]
    ).reshape(-1)[: B * T]
    wtables = tables.reshape(-1)

    def multi(params, kv):
        def body(kv, _):
            spec = llama.AttnSpec.gather(
                jnp.zeros((B, 8), jnp.int32), write_tables=wtables,
                page_size=PG, block_tables=tables,
                q_pos0=jnp.zeros((B,), jnp.int32),
                lengths=jnp.full((B,), T, jnp.int32),
            )
            hidden, kv = llama.forward(
                params, CFG, tokens, positions, kv, wslots, spec,
            )
            return kv, hidden[0, -1, 0]

        kv, outs = jax.lax.scan(body, kv, None, length=STEPS)
        return outs[-1], kv

    real = PF.flash_prefill_attention
    try:
        if knockout:
            PF.flash_prefill_attention = (
                lambda q, kc, vc, *a, **kw: jnp.zeros_like(q)
            )
        elif ppb or t_tile:
            kwov = {}
            if ppb:
                kwov["pages_per_block"] = ppb
            if t_tile:
                kwov["t_tile"] = t_tile
            PF.flash_prefill_attention = functools.partial(real, **kwov)
        f = jax.jit(multi, donate_argnums=(1,))
        out, kv = f(params, kv)
        _ = np.asarray(out)
        t0 = time.perf_counter()
        for _ in range(N):
            out, kv = f(params, kv)
        _ = np.asarray(out)
        return (time.perf_counter() - t0) / N / STEPS
    finally:
        PF.flash_prefill_attention = real


def main():
    toks = B * T
    for name, kw in (
        ("packed full", dict()),
        ("packed ppb=1", dict(ppb=1)),
        ("packed ppb=2", dict(ppb=2)),
        ("packed ppb=1 tt=256", dict(ppb=1, t_tile=256)),
        ("packed KNOCKOUT", dict(knockout=True)),
    ):
        dt = time_scan(**kw)
        print(
            f"{name:18s} {dt * 1e3:8.2f} ms/step -> {toks / dt / 1e3:7.1f}k tok/s",
            flush=True,
        )


if __name__ == "__main__":
    main()
