"""Roofline ledger: flight-recorder digests x collective counters.

Joins the two measurement planes this engine already carries into one
achieved-vs-peak table per dispatch kind:

- **Flight-recorder digests** (engine/flight_recorder.py): per-step
  kind/rows/tokens/wall sampled at the engine's `_phase_stats` sites —
  the wall-clock denominator.
- **Collective counters** (`{kind}_collective_bytes` +
  `collective_wall_s` in phase stats, fed by the tp_overlap byte
  formula in parallel/tp_overlap.py): the measured interconnect
  numerator GSPMD profiling never attributes per dispatch kind.

For each dispatch kind (prefill/decode/mixed/spec_verify) it reports
steps, tokens, wall, modeled dense-projection FLOP/s vs peak, modeled
weight+KV-write HBM traffic vs peak, and the measured collective bytes
vs ICI peak — enough to read which roof each phase sits under. The
FLOPs/HBM sides are MODELED from the model config (2 FLOPs per matmul
param per token; weights streamed once per dispatch; KV write bytes per
token); attention-score FLOPs and decode KV READS are workload-
dependent and excluded — the ledger says what a phase *at least* did,
not a profiler truth. The collective side is measured, not modeled.

Input modes:
  python scripts/roofline.py                    # self-contained demo:
      8 virtual CPU devices, tp=8 tp_overlap engine serves a few greedy
      streams, then the ledger runs on its own digests + counters
  python scripts/roofline.py --artifact X.json  # a flight-recorder
      artifact (watchdog/SLO dump or GET /debug/snapshot); digests +
      context.phase_stats come from the file, --model names the preset
  python scripts/roofline.py --json             # machine-readable
      ledger on stdout (either mode); scripts/bench_history.py-style
      tooling can join it to commits

Peaks default to one v5e chip (bf16 MXU 197 TFLOP/s, HBM 819 GB/s, ICI
~90 GB/s aggregate) — override for other parts; on the CPU demo the
percentages are illustrative only, the JOIN is what this script proves.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DISPATCH_KINDS = ("prefill", "decode", "mixed", "spec_verify")

# per-chip v5e peaks (the deployment part this repo targets)
PEAK_FLOPS = 197e12
PEAK_HBM = 819e9
PEAK_ICI = 90e9


def matmul_params(cfg) -> tuple[int, int]:
    """(per-layer-stack matmul params, vocab-head params) of the dense
    architecture — the 2-FLOPs-per-param-per-token roofline numerator."""
    per_layer = (
        cfg.hidden_size * cfg.q_size          # wq
        + 2 * cfg.hidden_size * cfg.kv_size   # wk, wv
        + cfg.q_size * cfg.hidden_size        # wo
        + 3 * cfg.hidden_size * cfg.intermediate_size  # gate/up/down
    )
    return cfg.num_layers * per_layer, cfg.hidden_size * cfg.vocab_size


def kv_write_bytes_per_token(
    cfg, itemsize: int = 2, kv_quant: str | None = None,
    kv_quant_group: int | None = None,
) -> int:
    """Per-token KV-write HBM bytes for the pool tier actually served.

    The quantized tiers write int8 bytes (one per element) or int4
    nibbles (half) plus the f32 scale channels (one per kv head for
    int8; head_dim/kv_quant_group per head for int4 — ops/quant.py), so
    a pallas+packed dispatch must NOT be read against the bf16 byte
    floor: at 8B dims the int8 tier's floor is ~0.53x bf16's and int4's
    ~0.28x — accounting them at bf16 bytes would overstate achieved HBM
    by 2-4x on exactly the legs the packed-KV executor serves."""
    k_and_v = 2 * cfg.kv_size * cfg.num_layers
    if kv_quant == "int8":
        scale_ch = cfg.num_kv_heads
        return k_and_v + 2 * scale_ch * 4 * cfg.num_layers
    if kv_quant == "int4":
        groups = cfg.head_dim // (kv_quant_group or cfg.head_dim)
        scale_ch = cfg.num_kv_heads * groups
        return k_and_v // 2 + 2 * scale_ch * 4 * cfg.num_layers
    if kv_quant is not None:
        raise ValueError(
            f"unknown kv_quant {kv_quant!r}; expected 'int8' or 'int4'"
        )
    return k_and_v * itemsize


def build_ledger(
    digests: list,
    fields: list,
    kinds: list,
    phase_stats: dict,
    cfg,
    itemsize: int = 2,
    peak_flops: float = PEAK_FLOPS,
    peak_hbm: float = PEAK_HBM,
    peak_ici: float = PEAK_ICI,
    kv_quant: str | None = None,
    kv_quant_group: int | None = None,
) -> dict:
    """The join: digest rows keyed by kind x the per-kind collective
    counters, normalized into achieved-vs-peak rates."""
    col = {f: i for i, f in enumerate(fields)}
    kind_name = {i: k for i, k in enumerate(kinds)}
    stack_params, _head_params = matmul_params(cfg)
    flops_per_tok = 2 * stack_params
    weight_bytes = stack_params * itemsize
    kv_write_per_tok = kv_write_bytes_per_token(
        cfg, itemsize, kv_quant, kv_quant_group
    )

    ledger = {}
    for kind in DISPATCH_KINDS:
        rows = [
            d for d in digests
            if kind_name.get(int(d[col["kind"]])) == kind
        ]
        if not rows:
            continue
        steps = len(rows)
        tokens = int(sum(d[col["tokens"]] for d in rows))
        wall = float(sum(d[col["wall_s"]] for d in rows))
        flops = tokens * flops_per_tok
        # HBM floor: weights streamed once per dispatch + KV writes
        hbm = steps * weight_bytes + tokens * kv_write_per_tok
        coll = int(phase_stats.get(f"{kind}_collective_bytes", 0))
        entry = {
            "steps": steps,
            "tokens": tokens,
            "wall_s": round(wall, 6),
            "model_flops": flops,
            "model_hbm_bytes": hbm,
            "collective_bytes": coll,
        }
        if wall > 0:
            entry.update({
                "achieved_tflops": round(flops / wall / 1e12, 6),
                "pct_peak_flops": round(100 * flops / wall / peak_flops, 4),
                "achieved_hbm_gbps": round(hbm / wall / 1e9, 6),
                "pct_peak_hbm": round(100 * hbm / wall / peak_hbm, 4),
                "collective_gbps": round(coll / wall / 1e9, 6),
                "pct_peak_ici": round(100 * coll / wall / peak_ici, 4),
                # bytes per FLOP the phase actually ran at — compare
                # against peak_flops/peak_hbm to see which roof binds
                "arithmetic_intensity": round(flops / max(hbm, 1), 3),
            })
        ledger[kind] = entry

    total_coll = sum(
        int(v) for k, v in phase_stats.items()
        if k.endswith("_collective_bytes")
    )
    return {
        "model": cfg.name,
        "itemsize": itemsize,
        "kv_quant": kv_quant,
        "kv_write_bytes_per_token": kv_write_per_tok,
        "flops_per_token": flops_per_tok,
        "weight_stream_bytes": weight_bytes,
        "peaks": {"flops": peak_flops, "hbm": peak_hbm, "ici": peak_ici},
        "kinds": ledger,
        "collective": {
            "total_bytes": total_coll,
            "wall_s_est": round(
                float(phase_stats.get("collective_wall_s", 0.0)), 6
            ),
        },
        "note": (
            "FLOPs/HBM are modeled floors (dense projections; weights "
            "once per dispatch; KV writes) — attention scores and "
            "decode KV reads excluded; collective bytes are measured"
        ),
    }


def _demo() -> tuple[list, list, list, dict, object]:
    """Self-contained source: a tp=8 tp_overlap engine on 8 virtual CPU
    devices serves greedy streams; its own digests + counters feed the
    ledger (the same join a production artifact gets)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import asyncio

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.models import config as cfgmod
    from dynamo_tpu.parallel.mesh import MeshConfig
    from dynamo_tpu.runtime.pipeline.context import Context

    cfg = cfgmod.get_config("tiny").with_(num_heads=8, num_kv_heads=8)
    engine = JaxEngine(EngineConfig(
        model=cfg, dtype="float32", mesh=MeshConfig(tp=8),
        page_size=8, num_pages=96, max_batch_size=4, max_model_len=128,
        prefill_chunk=32, tp_overlap=True, seed=0,
    ))

    async def serve():
        async def one(prompt):
            pre = PreprocessedRequest(
                token_ids=list(prompt),
                stop_conditions=StopConditions(max_tokens=12),
                sampling_options=SamplingOptions(greedy=True),
            )
            return [
                f async for f in await engine.generate(Context(pre.to_dict()))
            ]

        await asyncio.gather(*(one(p) for p in (
            [5, 17, 42, 9, 88, 3], [11, 3, 7, 29, 31],
        )))

    asyncio.run(serve())
    digests = engine.flight.snapshot_rows()
    from dynamo_tpu.engine.flight_recorder import FIELDS, KINDS

    stats = engine.phase_stats
    asyncio.run(engine.close())
    return digests, list(FIELDS), list(KINDS), stats, engine.model_cfg


def _render(ledger: dict) -> str:
    lines = [
        "roofline ledger — model=%s (modeled floors vs per-chip peaks; "
        "collective bytes measured)" % ledger["model"],
        "%-12s %6s %8s %10s %10s %8s %10s %8s %12s" % (
            "kind", "steps", "tokens", "wall_s", "TFLOP/s", "%peak",
            "HBM GB/s", "%peak", "coll bytes",
        ),
    ]
    for kind, e in ledger["kinds"].items():
        lines.append(
            "%-12s %6d %8d %10.4f %10.4f %8.3f %10.4f %8.3f %12d" % (
                kind, e["steps"], e["tokens"], e["wall_s"],
                e.get("achieved_tflops", 0.0),
                e.get("pct_peak_flops", 0.0),
                e.get("achieved_hbm_gbps", 0.0),
                e.get("pct_peak_hbm", 0.0),
                e["collective_bytes"],
            )
        )
    c = ledger["collective"]
    lines.append(
        "collectives: %d bytes total, est wall %.4fs"
        % (c["total_bytes"], c["wall_s_est"])
    )
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--artifact",
        help="flight-recorder artifact JSON (digests + context."
             "phase_stats); default: run the self-contained demo engine",
    )
    ap.add_argument(
        "--model", default="tiny",
        help="model preset the artifact's engine served (tiny)",
    )
    ap.add_argument(
        "--itemsize", type=int, default=2,
        help="weight/KV element bytes (2 = bf16)",
    )
    ap.add_argument(
        "--kv-quant", choices=("int8", "int4"), default=None,
        help="KV pool tier the engine served: int8/int4 write quantized "
             "bytes + f32 scale tiles, not --itemsize bytes (the "
             "pallas+packed legs must not be read against bf16 floors)",
    )
    ap.add_argument(
        "--kv-quant-group", type=int, default=None,
        help="int4 scale-group width in features (default head_dim: one "
             "scale group per kv head)",
    )
    ap.add_argument("--peak-flops", type=float, default=PEAK_FLOPS)
    ap.add_argument("--peak-hbm", type=float, default=PEAK_HBM)
    ap.add_argument("--peak-ici", type=float, default=PEAK_ICI)
    ap.add_argument(
        "--json", action="store_true",
        help="machine-readable ledger on stdout instead of the table",
    )
    args = ap.parse_args()

    if args.artifact:
        with open(args.artifact) as f:
            art = json.load(f)
        from dynamo_tpu.models.config import get_config

        digests = art["digests"]
        fields = art["digest_fields"]
        kinds = art["digest_kinds"]
        stats = (art.get("context") or {}).get("phase_stats") or {}
        cfg = get_config(args.model)
    else:
        digests, fields, kinds, stats, cfg = _demo()

    ledger = build_ledger(
        digests, fields, kinds, stats, cfg,
        itemsize=args.itemsize, peak_flops=args.peak_flops,
        peak_hbm=args.peak_hbm, peak_ici=args.peak_ici,
        kv_quant=args.kv_quant, kv_quant_group=args.kv_quant_group,
    )
    if args.json:
        print(json.dumps(ledger, indent=2))
    else:
        print(_render(ledger))


if __name__ == "__main__":
    main()
