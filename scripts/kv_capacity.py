"""KV-capacity census: what the int4 tier buys at a fixed HBM budget.

    python scripts/kv_capacity.py            # JSON section on stdout
    BENCH_KV_CAPACITY=1 python bench.py      # same, as BENCH_OUT section

Three legs, one section:

- **capacity** — engines at ``dtype=bfloat16`` with the KV tier swept
  bf16 / int8 / int4, page bytes measured off the LIVE pool arrays
  (never re-derived from a formula that could drift from the
  allocator), then max resident streams at a fixed byte budget for a
  given ISL+OSL. The data-only byte ratio bf16:int4 is exactly 4.0 by
  construction (2 bytes -> half a byte per feature) and is asserted
  downstream by CI; the stream-capacity ratio includes the f32 scale
  tiles so it lands lower at tiny scale (scales amortize with
  head_dim — at the 8B north-star head_dim=128 the scale overhead is
  ~3%, at tiny head_dim=16 it is ~25%).
- **throughput** — a saturating greedy decode wave per quantized tier
  (conc = max_batch_size) on the gather backend. Reported, NOT
  CI-gated: CPU wall-clock jitter swamps the int4-vs-int8 delta at
  tiny scale; the on-TPU bench rig is where the bandwidth win shows.
- **quality** — model-level teacher-forced forward at f32 weights,
  f32-KV logits vs quantized-KV logits on held random prompts.
  Headline metric is the **margin-stable greedy token match**: per-
  position argmax agreement restricted to positions whose bf16 top1-
  top2 logit margin clears tau = 3x the median margin-noise the tier
  itself induces (|delta(top1-top2)| per position). Random-init tiny
  weights produce near-tied logits everywhere (f32 top-3 within ~0.01),
  so the RAW match (also reported) mostly scores coin flips the
  quantizer cannot be blamed for; on trained checkpoints margins dwarf
  the noise floor, stable_frac -> 1, and the metric reduces to plain
  greedy token match. docs/kv_cache.md spells out the methodology.

``run(**overrides)`` returns the section dict; the ``scenario``
descriptor inside it is the comparability context bench_history keys
on (budget/ISL/OSL/group changes = not comparable, by design).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

TIERS = (None, "int8", "int4")  # None = engine-dtype (bf16) KV


def _defaults() -> dict:
    return dict(
        model="tiny",
        budget_mb=float(os.environ.get("BENCH_KV_CAPACITY_MB", "64")),
        isl=48,
        osl=16,
        page=8,
        # capacity census runs at deployment-representative head_dim:
        # the scale pool pads sublanes to max(8, num_kv_heads)
        # (ops/quant.kv_scale_subl), so at tiny head_dim=16 the padded
        # f32 tiles eat most of the int4 byte win — a pathology of the
        # debug shape, not the tier. head_dim=128 (every llama preset)
        # is where the capacity claim has to hold.
        census_head_dim=128,
        census_pages=32,      # census engines: just big enough to measure
        wave_pages=256,       # throughput engines: enough for the wave
        wave_requests=8,
        max_batch=4,
        kv_quant_group=None,  # features per int4 scale (None = head_dim)
        quality_bs=4,
        quality_len=64,
        seed=0,
    )


def _tier_name(q) -> str:
    return q or "bf16"


def _pool_bytes(engine) -> tuple[int, int]:
    """(data_bytes, scale_bytes) of the live device KV pool."""
    kv = engine.kv
    data = sum(a.nbytes for a in kv.k) + sum(a.nbytes for a in kv.v)
    scales = 0
    for name in ("ks", "vs"):
        tiles = getattr(kv, name, None)
        if tiles:
            scales += sum(a.nbytes for a in tiles)
    return data, scales


def capacity_census(d: dict) -> dict:
    """Max resident streams per KV tier at a fixed byte budget."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models import config as cfgmod

    cfg = cfgmod.get_config(d["model"])
    if d["census_head_dim"]:
        cfg = cfg.with_(
            name=f"{cfg.name}-hd{d['census_head_dim']}",
            head_dim=d["census_head_dim"],
        )
    budget = int(d["budget_mb"] * 1024 * 1024)
    isl, osl, ps = d["isl"], d["osl"], d["page"]
    tiers: dict[str, dict] = {}
    for quant in TIERS:
        e = JaxEngine(EngineConfig(
            model=cfg, dtype="bfloat16", kv_quantization=quant,
            page_size=ps, num_pages=d["census_pages"],
            max_batch_size=2, max_model_len=isl + osl + ps,
            prefill_chunk=isl, attn_backend="gather",
            **({} if d["kv_quant_group"] is None or quant != "int4"
               else {"kv_quant_group": d["kv_quant_group"]}),
        ))
        data, scales = _pool_bytes(e)
        n = d["census_pages"]
        page_data = data // n
        page_total = (data + scales) // n
        pages_in_budget = budget // page_total
        resident = pages_in_budget * ps // (isl + osl)
        tiers[_tier_name(quant)] = {
            "page_bytes_data": page_data,
            "page_bytes_total": page_total,
            "pages_in_budget": pages_in_budget,
            "resident_streams": resident,
        }
        asyncio.run(e.close())
    bf16, int4, int8 = tiers["bf16"], tiers["int4"], tiers["int8"]
    return {
        "budget_bytes": budget,
        "tiers": tiers,
        # data-only ratio is EXACT (4.0 / 2.0): pure pool-array
        # arithmetic, the thing CI pins. Stream capacity folds in the
        # f32 scale tiles + page-granularity floors.
        "data_ratio_int4_vs_bf16": round(
            bf16["page_bytes_data"] / int4["page_bytes_data"], 4
        ),
        "data_ratio_int8_vs_bf16": round(
            bf16["page_bytes_data"] / int8["page_bytes_data"], 4
        ),
        "capacity_ratio_int4_vs_bf16": round(
            int4["resident_streams"] / bf16["resident_streams"], 4
        ),
        "capacity_ratio_int8_vs_bf16": round(
            int8["resident_streams"] / bf16["resident_streams"], 4
        ),
    }


async def _decode_wave(d: dict, quant: str) -> dict:
    """Saturating greedy wave on one quantized tier; toks/s over the
    timed wave only (a warmup request eats the jit compiles first)."""
    import numpy as np

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models import config as cfgmod
    from dynamo_tpu.runtime.pipeline.context import Context

    cfg = cfgmod.get_config(d["model"])
    isl, osl = d["isl"], d["osl"]
    engine = JaxEngine(EngineConfig(
        model=cfg, dtype="float32", kv_quantization=quant,
        page_size=d["page"], num_pages=d["wave_pages"],
        max_batch_size=d["max_batch"],
        max_model_len=isl + osl + d["page"],
        prefill_chunk=isl, attn_backend="gather", seed=d["seed"],
    ))
    rng = np.random.RandomState(d["seed"])

    async def serve(prompt) -> int:
        pre = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(
                max_tokens=osl, ignore_eos=True
            ),
            sampling_options=SamplingOptions(greedy=True),
        )
        n = 0
        async for f in await engine.generate(Context(pre.to_dict())):
            n += len(f.get("token_ids") or [])
        return n

    prompts = [
        rng.randint(1, cfg.vocab_size, size=isl).tolist()
        for _ in range(d["wave_requests"] + 1)
    ]
    await serve(prompts[0])  # warmup: compiles + pool touch
    t0 = time.perf_counter()
    counts = await asyncio.gather(*(serve(p) for p in prompts[1:]))
    wall = time.perf_counter() - t0
    await engine.close()
    tokens = int(sum(counts))
    return {
        "tokens": tokens,
        "wall_s": round(wall, 4),
        "toks_per_sec": round(tokens / wall, 2) if wall else None,
    }


def throughput_wave(d: dict) -> dict:
    out = {
        q: asyncio.run(_decode_wave(d, q)) for q in ("int8", "int4")
    }
    i8, i4 = out["int8"]["toks_per_sec"], out["int4"]["toks_per_sec"]
    out["int4_vs_int8"] = round(i4 / i8, 4) if i8 else None
    return out


def quality_probe(d: dict) -> dict:
    """Margin-stable greedy token match vs the f32-KV reference (see
    module docstring for why raw match alone misleads at tiny scale)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models import config as cfgmod, llama

    cfg = cfgmod.get_config(d["model"])
    b, t = d["quality_bs"], d["quality_len"]
    key = jax.random.PRNGKey(d["seed"])
    params = llama.init_params(cfg, key, dtype=jnp.float32)
    tokens = jax.random.randint(
        jax.random.PRNGKey(d["seed"] + 7), (b, t), 1, cfg.vocab_size
    )
    positions = jnp.tile(jnp.arange(t), (b, 1))
    num_slots = b * t + d["page"]
    wslots = (jnp.arange(b * t) + d["page"]).astype(jnp.int32)
    smat = jnp.concatenate(
        [wslots.reshape(b, t), jnp.zeros((b, d["page"]), jnp.int32)],
        axis=1,
    )
    group = d["kv_quant_group"] or cfg.head_dim
    int4_groups = cfg.head_dim // group

    def run(quant):
        if quant is None:
            cache = llama.init_kv_cache(cfg, num_slots, dtype=jnp.float32)
            spec = llama.AttnSpec.gather(smat)
        else:
            cache = llama.init_kv_cache(
                cfg, num_slots, kv_quant=quant,
                kv_quant_group=group if quant == "int4" else None,
            )
            spec = llama.AttnSpec.gather(
                smat,
                int4_groups=int4_groups if quant == "int4" else 0,
            )
        h, _ = llama.forward(
            params, cfg, tokens, positions, cache, wslots, spec
        )
        return llama.logits(params, cfg, h.reshape(b * t, -1))

    lf = run(None)
    rows = jnp.arange(lf.shape[0])
    order = jnp.argsort(lf, -1)
    top1, top2 = order[:, -1], order[:, -2]
    margin = lf[rows, top1] - lf[rows, top2]
    tiers = {}
    for quant in ("int8", "int4"):
        lq = run(quant)
        aq = jnp.argmax(lq, -1)
        noise = jnp.abs((lq[rows, top1] - lq[rows, top2]) - margin)
        tau = 3.0 * float(jnp.median(noise))
        stable = margin >= tau
        tiers[quant] = {
            "greedy_token_match": round(
                float((aq[stable] == top1[stable]).mean()), 4
            ),
            "raw_match": round(float((aq == top1).mean()), 4),
            "stable_frac": round(float(stable.mean()), 4),
            "tau": round(tau, 6),
        }
    return {
        "method": (
            "teacher-forced f32-weight forward; greedy_token_match is "
            "argmax agreement on decision-stable positions (bf16 "
            "top1-top2 margin >= tau = 3x median quantization-induced "
            "margin noise); raw_match counts every position"
        ),
        "positions": int(b * t),
        "tiers": tiers,
    }


def run(**overrides) -> dict:
    d = {**_defaults(), **overrides}
    cap = capacity_census(d)
    thr = throughput_wave(d)
    qual = quality_probe(d)
    return {
        # comparability context for bench_history: a different budget,
        # shape, or group size is a different experiment
        "scenario": {
            "name": "kv_capacity",
            "model": d["model"],
            "budget_mb": d["budget_mb"],
            "isl": d["isl"],
            "osl": d["osl"],
            "page": d["page"],
            "census_head_dim": d["census_head_dim"],
            "kv_quant_group": d["kv_quant_group"],
            "wave_requests": d["wave_requests"],
            "max_batch": d["max_batch"],
            "seed": d["seed"],
        },
        "capacity": cap,
        "throughput": thr,
        "quality": qual,
        "extra": {"model": d["model"]},
        # tiny census engines cannot speak for real-rig throughput —
        # same convention as the headline's extra.headline_note
        "headline_note": (
            "capacity arithmetic is exact at any scale; the throughput "
            "legs ran the gather backend at tiny scale (CPU-safe) and "
            "do not predict on-TPU pallas bandwidth wins"
        ),
    }


if __name__ == "__main__":
    out = run()
    print(json.dumps(out, indent=2))
    cap, q = out["capacity"], out["quality"]["tiers"]
    ok = (
        cap["data_ratio_int4_vs_bf16"] == 4.0
        and cap["capacity_ratio_int4_vs_bf16"] >= 1.8
        and q["int4"]["greedy_token_match"] >= 0.95
        and q["int8"]["greedy_token_match"] >= 0.95
    )
    sys.exit(0 if ok else 1)
