"""Request-failover FLEET scenario: prove in-flight requests survive
worker death (docs/robustness.md "Request failover").

PR 6's breakers and PR 8's control loop recover the FLEET after a
worker dies; this scenario scores the missing third leg — the REQUESTS
that were streaming on the dead worker. Real components in one process:

    HubServer <- N x { JaxEngine + KvEventPublisher + KvMetricsPublisher
                       + KvExportHandler + PrefixPuller }   (workers on
        ^              the real data plane)
    frontend: discovery Client -> KvPushRouter (prefix-overlap routing)
              -> FailoverEngine (journal + replay) -> LIVE HttpService
              (greedy SSE streams over a real socket)

Three legs, each on a fresh fleet:

1. **cold** — concurrent greedy SSE streams; a ``dataplane.die`` fault
   (the DYN_FAULTS grammar, utils/faults.py) severs the serving
   worker's whole data plane mid-stream — on the wire identical to a
   SIGKILLed process. Every stream must complete **byte-identical** to
   the reference serve with zero duplicated or skipped tokens; the
   replay recomputes the continuation prompt (the recompute bar).
2. **reuse** — the stream prompts' shared prefix is warm on EVERY
   worker before the kill, so the KV-aware replay routes to a surviving
   holder and rides its prefix cache: ``reused`` continuation tokens
   replace recompute.
3. **pull** — the prefix is held ONLY by a saturated worker; the replay
   lands on an idle worker that PULLS the prefix from the holder
   (``kv_export`` -> ``ingest_prefix``, the PR 9 path) instead of
   recomputing it: ``pull`` tokens on the replay serve.

The reuse/pull kills abort the observed serving worker's data plane
directly (`DataPlaneServer._die_abruptly`, the exact action the
``dataplane.die`` fault point maps to) so the victim deterministically
holds live streams; the cold leg goes through the fault registry
itself to prove the DYN_FAULTS story end to end.

Scored (the ``failover`` BENCH_OUT section): per-leg and pooled
``recovered_frac`` (broken streams that finished clean),
``replay_ttft_gap_p50_s`` (how long the client stalled across the
death), and the continuation-token economics (recompute vs reused vs
pulled). Run directly it prints the JSON and exits non-zero when the
proof failed (a stream repeated/gapped a token, a broken stream was
lost, or the reuse/pull legs recomputed). Also registered in the
loadgen scenario registry as the ``failover`` adapter
(docs/loadgen.md), so ``scripts/run_scenarios.py`` runs this proof too.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402

from dynamo_tpu.engine.kv_ledger import quiesce_census  # noqa: E402
from dynamo_tpu.runtime.component import EndpointId  # noqa: E402
from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: E402
from dynamo_tpu.runtime.hub.server import HubServer  # noqa: E402
from dynamo_tpu.runtime.pipeline.context import Context  # noqa: E402
from dynamo_tpu.utils import counters, faults  # noqa: E402

NS, COMP, EP = "failover", "backend", "generate"

# counter keys snapshotted around every chaos burst (deltas = the score)
_KEYS = (
    "failover_replays_total",
    "failover_recovered_total",
    "failover_giveup_total",
    "failover_storm_shed_total",
    "failover_recompute_tokens_total",
    "failover_reused_tokens_total",
    "failover_pull_tokens_total",
)


def _defaults() -> dict:
    """Tiny-scale defaults (CPU CI finishes the three legs in ~1 min)."""
    return dict(
        page=16,               # KV page size (gather backend)
        prefix_pages=4,        # shared-prefix pages (reuse/pull legs)
        suffix=8,              # per-request fresh suffix tokens
        osl=32,                # generated tokens per stream
        streams=4,             # concurrent streams per chaos burst
        pull_streams=1,        # pull-leg streams: ONE, so the replay's
        #                        target is an idle worker that has never
        #                        seen the prefix (a second stream's own
        #                        first-serve pull would pre-warm it and
        #                        the replay would score as reuse)
        max_batch=4,           # decode slots per worker
        num_pages=256,
        hold_osl=96,           # held-stream length saturating the holder
        pull_threshold_pages=2,
        pull_busy_frac=0.7,    # saturation bar: the holder's looping
        #                        hold lanes dip a slot between rounds,
        #                        and a scrape catching the dip must not
        #                        read the holder as idle
        poll_interval_s=5.0,   # aggregator cadence (cold/reuse legs:
        #                        stats arrivals must not swallow the
        #                        frame-counted fault hit)
        pull_poll_interval_s=0.25,  # pull leg needs fresh saturation
        retry_budget=2,        # DYN_FAILOVER_RETRIES equivalent
    )


def _cfgs(d: dict):
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.models import config as cfgmod

    mcfg = cfgmod.get_config("tiny")
    isl = d["prefix_pages"] * d["page"] + d["suffix"]
    ecfg = EngineConfig(
        model=mcfg, dtype="float32", page_size=d["page"],
        num_pages=d["num_pages"], max_batch_size=d["max_batch"],
        max_model_len=isl + max(d["osl"], d["hold_osl"]) + 32,
        prefill_chunk=isl,
        # routing/replay economics, not kernels: the gather oracle runs
        # identically on CPU CI and on-TPU bench rigs
        attn_backend="gather",
    )
    return mcfg, ecfg, isl


@contextlib.asynccontextmanager
async def _fleet(
    d: dict, n_workers: int, poll_interval: float, census_out=None
):
    """Hub + n real workers (full KV plane) + the frontend failover
    stack behind a live HttpService; yields a handle dict.

    With `census_out` (a list), the teardown runs the zero-orphan
    quiesce census over the leg's engines BEFORE closing them and
    appends the result — a chaos-killed worker's engine outlives its
    data plane, so its severed streams' pages must still drain."""
    from dynamo_tpu.engine import JaxEngine
    from dynamo_tpu.llm.http.discovery import RouterEngine
    from dynamo_tpu.llm.http.failover import FailoverConfig, FailoverEngine
    from dynamo_tpu.llm.kv_router import (
        KvEventPublisher,
        KvMetricsPublisher,
        KvPushRouter,
        KvRouter,
    )
    from dynamo_tpu.llm.kv_router.pull import KvExportHandler, PrefixPuller
    from dynamo_tpu.loadgen.http import engine_http_service

    mcfg, ecfg, isl = _cfgs(d)
    hub = HubServer()
    await hub.start("127.0.0.1", 0)
    hub_addr = f"127.0.0.1:{hub.port}"
    eid = EndpointId(NS, COMP, EP)
    drts, engines = [], []
    try:
        for _ in range(n_workers):
            drt = await DistributedRuntime.from_settings(hub_addr=hub_addr)
            drts.append(drt)
            engine = JaxEngine(ecfg)
            engines.append(engine)
            ep = drt.namespace(NS).component(COMP).endpoint(EP)
            KvEventPublisher(
                ep.component, drt.primary_lease.lease_id
            ).attach(engine).start()
            await KvExportHandler(drt, engine, NS, COMP).start()
            puller = PrefixPuller(drt, engine, engine, eid)
            metrics = KvMetricsPublisher.for_engine(engine)
            await ep.serve_engine(puller, stats_handler=metrics.stats_handler)

        fe = await DistributedRuntime.from_settings(hub_addr=hub_addr)
        drts.append(fe)
        ep = fe.namespace(NS).component(COMP).endpoint(EP)
        client = await ep.client()
        for _ in range(200):
            if len(client.instance_ids()) >= n_workers:
                break
            await asyncio.sleep(0.05)
        router = KvRouter(
            ep.component, client, block_size=d["page"],
            poll_interval=poll_interval,
            pull_threshold_tokens=d["pull_threshold_pages"] * d["page"],
            pull_busy_frac=d["pull_busy_frac"],
        )
        await router.start()
        push = KvPushRouter(client, router)
        eng = FailoverEngine(
            RouterEngine(client, "kv", kv_router=push),
            client=client, drt=fe,
            cfg=FailoverConfig(max_retries=d["retry_budget"]),
        )
        async with engine_http_service(
            eng, vocab_size=mcfg.vocab_size
        ) as svc:
            yield {
                "failover": eng,
                "engines": engines,
                "worker_drts": drts[:n_workers],
                "client": client,
                "router": router,
                "svc": svc,
                "vocab": mcfg.vocab_size,
                "isl": isl,
            }
    finally:
        if census_out is not None:
            with contextlib.suppress(Exception):
                census_out.append(
                    await asyncio.to_thread(quiesce_census, engines)
                )
        for e in engines:
            with contextlib.suppress(Exception):
                await e.close()
        for drt in drts:
            with contextlib.suppress(Exception):
                await drt.shutdown()
        await hub.stop()


async def _warm_compile(fleet, d: dict, rng) -> None:
    """Pay every worker's prefill/decode + warm-continuation compile
    families before anything is measured."""
    for engine in fleet["engines"]:
        wp = rng.randint(1, fleet["vocab"], size=fleet["isl"]).tolist()
        for _ in range(2):
            await _direct_serve(engine, wp, d["osl"] // 4)


async def _direct_serve(engine, tokens, osl: int) -> list[int]:
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    pre = PreprocessedRequest(
        token_ids=list(tokens),
        stop_conditions=StopConditions(max_tokens=osl, ignore_eos=True),
        sampling_options=SamplingOptions(greedy=True),
    )
    out = []
    async for frame in await engine.generate(Context(pre.to_dict())):
        out.extend(frame.get("token_ids") or [])
    return out


async def _sse_stream(session, tokens, osl: int, rid: str) -> dict:
    """One greedy SSE stream; returns {ttft, texts, ok, error}."""
    body = {
        "model": "loadgen", "prompt": list(tokens), "stream": True,
        "max_tokens": osl,
        "dyn_ext": {"ignore_eos": True, "greed_sampling": True},
    }
    t0 = time.perf_counter()
    texts: list[str] = []
    ttft = None
    try:
        async with session.post(
            "/v1/completions", json=body, headers={"x-request-id": rid}
        ) as resp:
            if resp.status != 200:
                return {"ok": False, "ttft": None, "texts": texts,
                        "error": f"http {resp.status}"}
            async for raw in resp.content:
                line = raw.decode().rstrip("\n")
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data == "[DONE]":
                    break
                item = json.loads(data)
                text = "".join(
                    c.get("text") or "" for c in item.get("choices") or []
                )
                if text:
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    texts.append(text)
    except Exception as exc:  # noqa: BLE001 — a broken stream is data
        return {"ok": False, "ttft": ttft, "texts": texts,
                "error": f"{type(exc).__name__}: {exc}"}
    return {"ok": True, "ttft": ttft, "texts": texts, "error": None}


def _stream_ids(out: dict) -> list[str]:
    return "".join(out["texts"]).split()


async def _abort_serving_worker(fleet, victims: list[int]) -> int:
    """Wait until one of `victims` (engine indexes) is serving a
    journaled stream that has DELIVERED tokens (strictly mid-stream,
    not during prefill), then sever its whole data plane — the
    worker.die action, targeted so the death deterministically breaks
    live streams."""
    wids = {
        fleet["worker_drts"][i].primary_lease.lease_id: i for i in victims
    }
    for _ in range(4000):
        for s in fleet["failover"].live_streams():
            i = wids.get(s["instance"])
            if i is not None and s["emitted"] >= 2:
                fleet["worker_drts"][i].data_plane._die_abruptly()
                return i
        await asyncio.sleep(0.005)
    raise RuntimeError(f"no victim among {victims} ever served a stream")


def _leg_score(
    name: str, before: dict, outs: list[dict], refs: list[list[int]],
    replays_before: int, ttft_nofault: list,
) -> dict:
    from dynamo_tpu.llm.http import failover as fomod

    delta = {k: int(counters.get(k) - before[k]) for k in _KEYS}
    new_recs = fomod.recent_replays()[replays_before:]
    gaps = [r["gap_s"] for r in new_recs if r["gap_s"] is not None]
    identical = [
        _stream_ids(o) == [str(t) for t in ref]
        for o, ref in zip(outs, refs)
    ]
    broken = delta["failover_replays_total"] + delta["failover_giveup_total"]
    failures = (
        delta["failover_giveup_total"]
        + delta["failover_storm_shed_total"]
        + sum(1 for o in outs if not o["ok"])
    )
    ttfts = sorted(o["ttft"] for o in outs if o["ttft"] is not None)
    return {
        "streams": len(outs),
        "byte_identical": all(identical),
        "mismatches": [i for i, ok in enumerate(identical) if not ok],
        "broken": broken,
        "recovered": delta["failover_recovered_total"],
        "failed": failures,
        "recovered_frac": (
            round((broken - failures) / broken, 4) if broken else None
        ),
        "replay_gap_p50_s": (
            round(float(np.percentile(gaps, 50)), 4) if gaps else None
        ),
        "ttft_nofault_p50_s": (
            round(float(np.percentile(ttft_nofault, 50)), 4)
            if ttft_nofault else None
        ),
        "ttft_chaos_p50_s": (
            round(float(np.percentile(ttfts, 50)), 4) if ttfts else None
        ),
        "tokens": {
            "recompute": delta["failover_recompute_tokens_total"],
            "reused": delta["failover_reused_tokens_total"],
            "pull": delta["failover_pull_tokens_total"],
        },
        "replays": new_recs,
    }


async def _chaos_burst(fleet, session, prompts, refs, osl, kill) -> tuple:
    """Launch the streams, fire `kill` once they are mid-flight, gather.
    `kill` is ("faults", spec) or ("abort", [victim engine indexes])."""
    if kill[0] == "faults":
        faults.configure(kill[1])
        killer = None
    else:
        killer = asyncio.create_task(_abort_serving_worker(fleet, kill[1]))
    outs = await asyncio.gather(*(
        _sse_stream(session, p, osl, f"chaos-{i}")
        for i, p in enumerate(prompts)
    ))
    victim = None
    if killer is not None:
        with contextlib.suppress(Exception):
            victim = await asyncio.wait_for(killer, 5)
    faults.reset()
    return outs, victim


async def run_scenario(**overrides) -> dict:
    import aiohttp

    from dynamo_tpu.engine import JaxEngine
    from dynamo_tpu.llm.http import failover as fomod

    d = {**_defaults(), **overrides}
    rng = np.random.RandomState(11)
    mcfg, ecfg, isl = _cfgs(d)
    osl = d["osl"]

    # byte-identity oracle: a standalone engine with the identical
    # config serves every chaos prompt once — greedy decode is
    # deterministic across same-config engines, so these ARE the tokens
    # an uninterrupted fleet serve would stream
    ref_engine = JaxEngine(ecfg)

    async def refs_for(prompts):
        out = []
        for p in prompts:
            out.append(await _direct_serve(ref_engine, p, osl))
        return out

    def fresh_prompts(n):
        return [
            rng.randint(1, mcfg.vocab_size, size=isl).tolist()
            for _ in range(n)
        ]

    def prefixed_prompts(prefix, n):
        return [
            list(prefix)
            + rng.randint(1, mcfg.vocab_size, size=d["suffix"]).tolist()
            for _ in range(n)
        ]

    legs: dict[str, dict] = {}
    censuses: list[dict] = []
    try:
        # ---- leg 1: cold (DYN_FAULTS kill, recompute replay) ----------
        async with _fleet(
            d, 2, d["poll_interval_s"], census_out=censuses
        ) as fleet:
            await _warm_compile(fleet, d, rng)
            async with aiohttp.ClientSession(
                f"http://127.0.0.1:{fleet['svc'].port}"
            ) as session:
                bar = await asyncio.gather(*(
                    _sse_stream(session, p, osl, f"bar-{i}")
                    for i, p in enumerate(fresh_prompts(d["streams"]))
                ))
                ttft_bar = [o["ttft"] for o in bar if o["ttft"] is not None]
                # the fault fires on the Nth data-plane frame after
                # arming — mid-flight of the stream wave
                spec = f"dataplane.die.fail@{d['streams'] * osl // 2}x1"
                for attempt in range(2):
                    prompts = fresh_prompts(d["streams"])
                    refs = await refs_for(prompts)
                    before = {k: counters.get(k) for k in _KEYS}
                    n_recs = len(fomod.recent_replays())
                    outs, _ = await _chaos_burst(
                        fleet, session, prompts, refs, osl, ("faults", spec)
                    )
                    legs["cold"] = _leg_score(
                        "cold", before, outs, refs, n_recs, ttft_bar
                    )
                    legs["cold"]["faults"] = spec
                    if legs["cold"]["broken"] >= 1:
                        break
                    # the one-shot fault can land on a stats frame of a
                    # stream-less worker; re-arm once on fresh prompts

        # ---- leg 2: reuse (prefix warm fleet-wide; replay rides the
        # survivor's cache) ---------------------------------------------
        async with _fleet(
            d, 2, d["poll_interval_s"], census_out=censuses
        ) as fleet:
            await _warm_compile(fleet, d, rng)
            prefix = rng.randint(
                1, mcfg.vocab_size, size=d["prefix_pages"] * d["page"]
            ).tolist()
            for engine in fleet["engines"]:
                await _direct_serve(
                    engine,
                    prefix + rng.randint(
                        1, mcfg.vocab_size, size=2
                    ).tolist(),
                    2,
                )
            async with aiohttp.ClientSession(
                f"http://127.0.0.1:{fleet['svc'].port}"
            ) as session:
                prompts = prefixed_prompts(prefix, d["streams"])
                refs = await refs_for(prompts)
                before = {k: counters.get(k) for k in _KEYS}
                n_recs = len(fomod.recent_replays())
                outs, victim = await _chaos_burst(
                    fleet, session, prompts, refs, osl, ("abort", [0, 1])
                )
                legs["reuse"] = _leg_score(
                    "reuse", before, outs, refs, n_recs, []
                )
                legs["reuse"]["victim"] = victim

        # ---- leg 3: pull (prefix only on a saturated holder; the
        # replay PULLS it instead of recomputing) -----------------------
        async with _fleet(
            d, 3, d["pull_poll_interval_s"], census_out=censuses
        ) as fleet:
            await _warm_compile(fleet, d, rng)
            prefix = rng.randint(
                1, mcfg.vocab_size, size=d["prefix_pages"] * d["page"]
            ).tolist()
            holder = 0
            await _direct_serve(
                fleet["engines"][holder],
                prefix + rng.randint(1, mcfg.vocab_size, size=2).tolist(),
                2,
            )
            want_blocks = d["prefix_pages"]
            for _ in range(200):
                if fleet["router"].indexer.tree.num_blocks >= want_blocks:
                    break
                await asyncio.sleep(0.05)
            # byte-identity refs BEFORE saturating (the ref engine must
            # not compete with the held lanes for CPU)
            prompts = prefixed_prompts(prefix, d["pull_streams"])
            refs = await refs_for(prompts)
            # saturate the holder and KEEP it saturated: each lane
            # re-serves as soon as its stream finishes, so the
            # aggregator reads full slots at the first-serve decision
            # AND at the replay decision after the kill
            stop_hold = asyncio.Event()

            async def hold_lane(lane_prompt):
                # ONE fixed prompt per lane, re-served in a loop: slots
                # stay full but the holder's cache usage stays bounded
                # (fresh prompts each round would balloon usage and sink
                # the holder's selector logit below the idle workers —
                # then the replay routes cold and no pull ever fires)
                while not stop_hold.is_set():
                    with contextlib.suppress(Exception):
                        await _direct_serve(
                            fleet["engines"][holder], lane_prompt,
                            d["hold_osl"],
                        )

            # max_batch + 2 lanes: the two surplus lanes keep the
            # holder's WAITING queue non-empty, so a scrape landing in
            # a lane-restart dip still reads saturated (the router's
            # _saturated() honors queue depth as well as slots)
            held = [
                asyncio.create_task(hold_lane(
                    rng.randint(1, mcfg.vocab_size, size=isl).tolist()
                ))
                for _ in range(d["max_batch"] + 2)
            ]
            agg = fleet["router"].aggregator
            holder_wid = fleet["worker_drts"][holder].primary_lease.lease_id
            for _ in range(400):
                m = agg.current.endpoints.get(holder_wid)
                if m is not None and m.request_active_slots >= d["max_batch"]:
                    break
                await asyncio.sleep(d["pull_poll_interval_s"] / 2)
            async with aiohttp.ClientSession(
                f"http://127.0.0.1:{fleet['svc'].port}"
            ) as session:
                before = {k: counters.get(k) for k in _KEYS}
                n_recs = len(fomod.recent_replays())
                outs, victim = await _chaos_burst(
                    fleet, session, prompts, refs, osl, ("abort", [1, 2])
                )
                legs["pull"] = _leg_score(
                    "pull", before, outs, refs, n_recs, []
                )
                legs["pull"]["victim"] = victim
                legs["pull"]["pulls_landed"] = int(
                    counters.get("kv_pull_landed_total")
                )
            stop_hold.set()
            for t in held:
                t.cancel()
            with contextlib.suppress(Exception):
                await asyncio.gather(*held, return_exceptions=True)
    finally:
        with contextlib.suppress(Exception):
            await ref_engine.close()
        faults.reset()

    gaps = [
        r["gap_s"] for leg in legs.values() for r in leg["replays"]
        if r["gap_s"] is not None
    ]
    broken = sum(leg["broken"] for leg in legs.values())
    failed = sum(leg["failed"] for leg in legs.values())
    tokens = {
        k: sum(leg["tokens"][k] for leg in legs.values())
        for k in ("recompute", "reused", "pull")
    }
    # zero-orphan gate: every leg's fleet drained custody at teardown —
    # a chaos kill that stranded KV pages fails the proof even when all
    # the streams came back byte-identical
    cviol: dict[str, int] = {}
    for c in censuses:
        for k, v in (c.get("violations") or {}).items():
            cviol[k] = cviol.get(k, 0) + int(v)
    kv_census = {
        "fleets": len(censuses),
        "engines": sum(c["engines"] for c in censuses),
        "ok": bool(censuses) and all(c["ok"] for c in censuses),
        "orphan_pages": sum(
            len(c.get("orphan_pages") or []) for c in censuses
        ),
        "violations": cviol,
        "per_fleet": censuses,
    }
    return {
        "scenario": {
            k: d[k]
            for k in ("page", "prefix_pages", "suffix", "osl", "streams",
                      "pull_streams", "max_batch", "retry_budget")
        },
        "legs": legs,
        "byte_identical": all(leg["byte_identical"] for leg in legs.values()),
        "broken_streams": broken,
        "recovered_frac": (
            round((broken - failed) / broken, 4) if broken else None
        ),
        "replay_ttft_gap_p50_s": (
            round(float(np.percentile(gaps, 50)), 4) if gaps else None
        ),
        "tokens": tokens,
        "kv_census": kv_census,
    }


def run(**overrides) -> dict:
    return asyncio.run(run_scenario(**overrides))


def proof_ok(out: dict) -> bool:
    legs = out["legs"]
    return bool(
        out["byte_identical"]
        and out["recovered_frac"] == 1.0
        and out["broken_streams"] >= 2
        and legs["cold"]["tokens"]["recompute"] > 0
        and legs["reuse"]["tokens"]["reused"] > 0
        and legs["pull"]["tokens"]["pull"] > 0
        and out["kv_census"]["ok"]
    )


if __name__ == "__main__":
    out = run()
    print(json.dumps(out, indent=2))
    if not proof_ok(out):
        print("request failover proof FAILED", file=sys.stderr)
        sys.exit(1)
    print(
        f"failover proof: {out['broken_streams']} broken streams all "
        f"recovered byte-identical; replay gap p50 "
        f"{out['replay_ttft_gap_p50_s']}s; tokens {out['tokens']}",
        file=sys.stderr,
    )
    sys.exit(0)
