"""Focused decode-rate check: 16-layer 1B model scan at one batch size,
int8 weights + int8 KV (the flagship config). Same methodology as
scripts/kernel_check_tpu.py (full scan, fetch once) — used to iterate on
decode-kernel changes without the full check matrix.

Run: python scripts/probe_decode_full.py [B] [reps]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import get_config
from dynamo_tpu.ops.sampling import sample_tokens

B = int(sys.argv[1]) if len(sys.argv) > 1 else 256
N = int(sys.argv[2]) if len(sys.argv) > 2 else 6
CFG = get_config("llama-3.2-1b")
STEPS = 16
KV_LEN = 480


def time_scan(b, quant=True, kv_quant=True):
    pg = 128
    w_pages = -(-(KV_LEN + STEPS + pg) // pg)
    num_slots = (b * w_pages + 17) * pg
    tables = jnp.asarray(
        np.stack([np.arange(1 + i * w_pages, 1 + (i + 1) * w_pages)
                  for i in range(b)]), jnp.int32)
    temp = jnp.zeros((b,), jnp.float32)
    topk = jnp.zeros((b,), jnp.int32)
    topp = jnp.ones((b,), jnp.float32)

    def multi(params, kv, tokens, positions, key):
        def body(carry, _):
            tokens, positions, kv, key = carry
            key, sub = jax.random.split(key)
            wslots = (
                jnp.take_along_axis(
                    tables, (positions // pg)[:, None], axis=1
                )[:, 0] * pg + positions % pg
            ).astype(jnp.int32)
            spec = llama.AttnSpec.pallas_decode(
                tables, positions + 1, pg, write_pos=positions
            )
            hidden, kv = llama.forward(
                params, CFG, tokens[:, None], positions[:, None],
                kv, wslots, spec,
            )
            lg = llama.logits(params, CFG, hidden[:, 0])
            toks = sample_tokens(lg, sub, temp, topk, topp, all_greedy=True)
            return (toks, positions + 1, kv, key), toks

        (_, _, kv, _), out = jax.lax.scan(
            body, (tokens, positions, kv, key), None, length=STEPS)
        return out, kv

    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    if quant:
        from dynamo_tpu.ops.quant import quantize_params

        params = quantize_params(params, CFG)
    kv = jax.device_put(llama.init_kv_cache(
        CFG, num_slots, dtype=jnp.bfloat16,
        kv_quant="int8" if kv_quant else None, page_size=pg,
    ))
    tokens = jnp.ones((b,), jnp.int32)
    positions = jnp.full((b,), KV_LEN, jnp.int32)
    key = jax.random.PRNGKey(0)
    f = jax.jit(multi, donate_argnums=(1,))
    out, kv = f(params, kv, tokens, positions, key)
    _ = np.asarray(out[-1, :1])
    t0 = time.perf_counter()
    for _ in range(N):
        out, kv = f(params, kv, tokens, positions, key)
    _ = np.asarray(out[-1, :1])
    return (time.perf_counter() - t0) / N / STEPS


def main():
    dt = time_scan(B)
    print(f"B={B} int8+int8kv: {dt * 1e3:.3f} ms/step -> {B / dt:.0f} tok/s")


if __name__ == "__main__":
    main()
