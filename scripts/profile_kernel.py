"""Compiled pallas decode kernel on the real chip: correctness vs oracle +
timing vs the jnp gather path, at bench-like shapes.

Run: python scripts/profile_kernel.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.ops.attention import paged_attention, slots_from_pages
from dynamo_tpu.ops.pallas_attention import paged_decode_attention

PAGE = 16
B = 8
H, KH, HD = 32, 8, 64      # llama-3.2-1b heads
W = 38                      # 608-token context
DTYPE = jnp.bfloat16


def timeit(name, fn, *args, n=20, **kw):
    jax.block_until_ready(fn(*args, **kw))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:50s} {dt*1000:9.3f} ms")
    return dt


def main():
    rng = np.random.RandomState(0)
    num_pages = B * W + 1
    num_slots = num_pages * PAGE
    k_cache = jnp.asarray(rng.randn(num_slots, KH, HD), DTYPE)
    v_cache = jnp.asarray(rng.randn(num_slots, KH, HD), DTYPE)
    q = jnp.asarray(rng.randn(B, H, HD), DTYPE)
    lengths = np.asarray([600, 600, 600, 600, 600, 600, 600, 600], np.int32)
    tables = np.zeros((B, W), np.int32)
    for i in range(B):
        used = -(-lengths[i] // PAGE)
        tables[i, :used] = 1 + i * W + np.arange(used)
    tables = jnp.asarray(tables)
    lens = jnp.asarray(lengths)

    # correctness (compiled, real chip)
    got = paged_decode_attention(
        q, k_cache, v_cache, tables, lens, page_size=PAGE, pages_per_block=8
    )
    smat = slots_from_pages(tables, PAGE)
    want = paged_attention(q[:, None], k_cache, v_cache, smat, (lens - 1)[:, None])[:, 0]
    err = np.max(np.abs(np.asarray(got, np.float32) - np.asarray(want, np.float32)))
    print(f"max abs err vs oracle (bf16): {err:.4f}")

    f = jax.jit(
        lambda q, kc, vc, t, l: paged_decode_attention(
            q, kc, vc, t, l, page_size=PAGE, pages_per_block=8
        )
    )
    g = jax.jit(
        lambda q, kc, vc, smat, pos: paged_attention(q[:, None], kc, vc, smat, pos)
    )
    pos = (lens - 1)[:, None]
    t_k = timeit("pallas decode kernel", f, q, k_cache, v_cache, tables, lens)
    t_g = timeit("jnp gather attention", g, q, k_cache, v_cache, smat, pos)
    print(f"speedup: {t_g / t_k:.2f}x")

    # 16x back-to-back (amortize dispatch like the scan does)
    @jax.jit
    def f16(q, kc, vc, t, l):
        def body(i, acc):
            return acc + paged_decode_attention(
                q, kc, vc, t, l, page_size=PAGE, pages_per_block=8
            ).astype(jnp.float32)
        return jax.lax.fori_loop(0, 16, body, jnp.zeros((B, H, HD), jnp.float32))

    @jax.jit
    def g16(q, kc, vc, smat, pos):
        def body(i, acc):
            return acc + paged_attention(q[:, None], kc, vc, smat, pos)[
                :, 0
            ].astype(jnp.float32)
        return jax.lax.fori_loop(0, 16, body, jnp.zeros((B, H, HD), jnp.float32))

    t_k16 = timeit("pallas kernel x16 in-jit", f16, q, k_cache, v_cache, tables, lens, n=5)
    t_g16 = timeit("jnp gather x16 in-jit", g16, q, k_cache, v_cache, smat, pos, n=5)
    print(f"per-call: pallas {t_k16/16*1000:.3f} ms, gather {t_g16/16*1000:.3f} ms, "
          f"speedup {t_g16 / t_k16:.2f}x")


if __name__ == "__main__":
    main()
