"""TP comm/compute overlap bench: serialized psums vs the ring executor.

Subprocess behind bench.py's `tp_overlap` BENCH_OUT section
(BENCH_TP_OVERLAP=1): bench.py initializes jax against the real
backend long before the section runs, and this bench needs its OWN
8-virtual-device CPU mesh — so it runs as a child process that forces
the platform before the jax import and prints ONE JSON line on stdout.

What it measures (parallel/tp_overlap.py, docs/parallelism.md):

- **Per-layer step wall, serialized vs overlapped** — the same
  `layer_step` under `single_layer_executor` with the two psums intact
  vs decomposed into ring reduce-scatter + matmul-fused all-gather
  (warmup + best-of-N). On virtual CPU devices the rings run
  sequentially, so this wall is a scheduling-shape datum, not a
  speedup claim — the TPU latency-hiding scheduler is what cashes the
  overlap in; the invariant CI gates on is the byte ledger.
- **Measured collective bytes** — `record_collectives()` armed around
  each leg's trace: exposed bytes (standalone collectives on the
  critical path) must read EXACTLY 0.5x the serialized leg's, total
  wire bytes must be conserved (RS+AG re-schedules traffic, it does
  not remove any), and both must match `collective_bytes_per_layer`'s
  closed form.
- **Greedy byte-identity** — `tp_overlap_forward` argmax tokens vs the
  tp=1 `llama.forward` (the FP reduction-order invariant the serving
  path relies on).
- **Pallas + packed-KV legs** (`pallas_legs` in the JSON): the same
  invariants on the PRODUCTION serving combination — pallas prefill
  kernels (interpret mode on CPU) over int32-PACKED int8 and int4
  pools, whole-forward through `tp_overlap_forward` vs (a) tp=1 with
  the same kernels and (b) the GSPMD-fallback leg (per-layer kernel
  shard_maps + GSPMD-inserted psums, what `tp_overlap=False` serves).
  Gated: greedy byte-identity vs tp=1, the per-layer-segment exposed
  bytes exactly 0.5x the serialized closed form, total wire bytes
  conserved, and per-layer wall bounded vs the fallback leg (see
  PALLAS_WALL_SLACK — virtual CPU devices serialize the ring chunk
  ops a real rig overlaps, so the CPU gate bounds the known
  serialization cost rather than asserting a speedup).

Run:  python scripts/tp_overlap_bench.py        (~4 min on CPU)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from dynamo_tpu import compat  # noqa: E402
from dynamo_tpu.models import config as cfgmod, llama  # noqa: E402
from dynamo_tpu.parallel import mesh as meshmod  # noqa: E402
from dynamo_tpu.parallel import tp_overlap as ov  # noqa: E402

TP = 8
B = int(os.environ.get("BENCH_TP_OVERLAP_B", "4"))
T = int(os.environ.get("BENCH_TP_OVERLAP_T", "16"))
REPS = int(os.environ.get("BENCH_TP_OVERLAP_REPS", "30"))

# tiny widened to 8 query + 8 kv heads so the head shards survive tp=8
# (the same shape the multichip smoke serves)
CFG = cfgmod.get_config("tiny").with_(
    dtype="float32", num_layers=2, num_heads=8, num_kv_heads=8
)


def _inputs(b, t, page=8):
    rng = np.random.RandomState(0)
    tokens = rng.randint(1, CFG.vocab_size, (b, t)).astype(np.int32)
    positions = np.tile(np.arange(t, dtype=np.int32), (b, 1))
    wslots = np.stack(
        [np.arange(page * (1 + 8 * i), page * (1 + 8 * i) + t) for i in range(b)]
    ).astype(np.int32)
    return tokens, positions, wslots, wslots.copy()


# CPU-noise slack on the pallas-leg wall gate. Both legs run the same 8
# sequential interpret-kernel shard bodies, but the overlap executor's
# decomposed rings issue ~n chunked ppermute+matmul ops where GSPMD
# fuses one psum — traffic a real rig hides under the MXU, but on
# virtual CPU devices every chunk op is serialized wall time (measured
# ~2.8x on an idle 8-core host). The default slack bounds that known
# serialization cost so a genuine compute regression in the executor
# (say, re-quantizing per ring chunk) still reads red; on the real rig
# set BENCH_TP_OVERLAP_WALL_SLACK=1.0 to assert the actual "no worse
# than fallback" property the overlap claims.
WALL_SLACK = float(os.environ.get("BENCH_TP_OVERLAP_WALL_SLACK", "1.5"))
PALLAS_WALL_SLACK = float(
    os.environ.get("BENCH_TP_OVERLAP_PALLAS_WALL_SLACK", "4.0")
)


def _pallas_leg(tier: str, params, mesh) -> dict:
    """One pallas+packed-KV leg: interpret-mode page-scatter write +
    flash prefill over int32-packed `tier` pools, tp=8 overlap executor
    vs tp=1 and vs the GSPMD fallback (per-layer kernel shard_maps)."""
    page = 8
    tokens, positions, wslots, _ = _inputs(B, T, page=page)
    ppseq = T // page
    btables = np.stack(
        [np.arange(1 + 8 * i, 1 + 8 * i + ppseq) for i in range(B)]
    ).astype(np.int32)
    wtables = btables.reshape(-1)
    smat = (
        btables[:, :, None] * page + np.arange(page, dtype=np.int32)
    ).reshape(B, -1)
    groups = 1 if tier == "int4" else 0

    def spec(kv_tp, with_mesh):
        return llama.AttnSpec.gather(
            jnp.asarray(smat), write_tables=jnp.asarray(wtables),
            page_size=page, interpret=True,
            mesh=mesh if with_mesh else None,
            block_tables=jnp.asarray(btables),
            q_pos0=jnp.zeros(B, jnp.int32),
            lengths=jnp.full(B, T, jnp.int32),
            kv_tp=kv_tp, int4_groups=groups,
        )

    def fresh_kv(tp):
        return llama.init_kv_cache(
            CFG, 512, kv_quant=tier, page_size=page, tp=tp, packed=True
        )

    tok_j, pos_j = jnp.asarray(tokens), jnp.asarray(positions)
    ws_j = jnp.asarray(wslots.reshape(-1))

    # tp=1 reference: same interpret kernels, mesh-free spec
    ref_hidden, _ = llama.forward(
        params, CFG, tok_j, pos_j, fresh_kv(1), ws_j, spec(1, False)
    )
    ref_tok = np.asarray(
        jnp.argmax(llama.logits(params, CFG, ref_hidden[:, -1]), -1)
    )

    # overlap executor leg — ledger armed around the trace
    spec8 = spec(TP, False)
    ov_fn = jax.jit(
        lambda p, kv: ov.tp_overlap_forward(
            p, CFG, tok_j, pos_j, kv, ws_j, spec8, mesh
        )
    )
    kv8 = fresh_kv(TP)
    with compat.set_mesh(mesh):
        with ov.record_collectives() as led:
            hidden = jax.block_until_ready(ov_fn(params, kv8)[0])
        ov_walls = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            jax.block_until_ready(ov_fn(params, kv8)[0])
            ov_walls.append(time.perf_counter() - t0)
    ov_tok = np.asarray(
        jnp.argmax(llama.logits(params, CFG, hidden[:, -1]), -1)
    )

    # GSPMD fallback leg: sharded params, per-layer kernel shard_maps,
    # XLA-inserted psums (what tp_overlap=False serves on this shape)
    sh_params = meshmod.shard_params(params, CFG, mesh)
    kv_sh = meshmod.kv_cache_sharding(mesh)
    kv8_fb = jax.tree.map(lambda a: jax.device_put(a, kv_sh), fresh_kv(TP))
    fb_spec = spec(TP, True)
    fb_fn = jax.jit(
        lambda p, kv: llama.forward(
            p, CFG, tok_j, pos_j, kv, ws_j, fb_spec
        )
    )
    with compat.set_mesh(mesh):
        fb_hidden = jax.block_until_ready(fb_fn(sh_params, kv8_fb)[0])
        fb_walls = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            jax.block_until_ready(fb_fn(sh_params, kv8_fb)[0])
            fb_walls.append(time.perf_counter() - t0)
    fb_tok = np.asarray(
        jnp.argmax(llama.logits(params, CFG, fb_hidden[:, -1]), -1)
    )

    # byte ledger: per-layer segment exposed = exactly half the
    # serialized closed form; the one standalone final all-gather
    # (residual reassembly after the last layer) rides on top
    nl = CFG.num_layers
    rs = (TP - 1) * B * T * CFG.hidden_size * 4 // TP
    seg_exposed = led.exposed - rs
    serialized = nl * ov.collective_bytes_per_layer(
        CFG.hidden_size, B * T, TP, itemsize=4, overlap=False
    )
    assert seg_exposed * 2 == serialized, (tier, led.exposed, serialized)
    assert led.total - rs == serialized, (tier, led.total, serialized)

    identical = bool(np.array_equal(ref_tok, ov_tok))
    assert identical, (tier, ref_tok, ov_tok)
    assert np.array_equal(ref_tok, fb_tok), (tier, ref_tok, fb_tok)

    ov_layer = min(ov_walls) / nl
    fb_layer = min(fb_walls) / nl
    assert ov_layer <= fb_layer * PALLAS_WALL_SLACK, (
        tier, ov_layer, fb_layer
    )

    return {
        "kv_tier": tier,
        "backend": "pallas-interpret",
        "kv_packed": True,
        "layer_step_wall_s": round(ov_layer, 6),
        "fallback_layer_step_wall_s": round(fb_layer, 6),
        "exposed_bytes": led.exposed,
        "overlapped_bytes": led.overlapped,
        "total_bytes": led.total,
        "final_gather_bytes": rs,
        "exposed_ratio": seg_exposed / serialized,
        "total_bytes_conserved": True,
        "greedy_byte_identical_vs_tp1": identical,
        "wall_gate_slack": PALLAS_WALL_SLACK,
    }


def run() -> dict:
    assert jax.device_count() == 8, jax.device_count()
    mesh = meshmod.build_mesh(meshmod.MeshConfig(tp=TP))
    tokens, positions, wslots, smat = _inputs(B, T)
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    x = np.asarray(params["embed"])[tokens].astype(np.float32)
    from dynamo_tpu.ops.rope import rope_cos_sin, rope_inv_freq

    cos, sin = rope_cos_sin(
        jnp.asarray(rope_inv_freq(CFG)), jnp.asarray(positions)
    )
    kv = llama.init_kv_cache(CFG, 512, dtype=jnp.float32)
    lp = params["layers"][0]
    args = (
        lp, kv.k[0], kv.v[0], jnp.asarray(x), cos, sin,
        jnp.asarray(wslots.reshape(-1)), jnp.asarray(smat),
        jnp.asarray(positions),
    )

    legs = {}
    for name, overlap in (("serialized", False), ("overlap", True)):
        step = ov.single_layer_executor(
            CFG, mesh, B, T, page_size=8, overlap=overlap
        )
        # arm the ledger around the TRACE (first call compiles): the
        # executor returns the overlap leg still scattered, so the
        # ledger sees exactly one layer's collectives — no amortization
        with ov.record_collectives() as led:
            jax.block_until_ready(step(*args))
        walls = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            jax.block_until_ready(step(*args))
            walls.append(time.perf_counter() - t0)
        legs[name] = {
            "layer_step_wall_s": round(min(walls), 6),
            "exposed_bytes": led.exposed,
            "overlapped_bytes": led.overlapped,
            "total_bytes": led.total,
        }

    base, over = legs["serialized"], legs["overlap"]
    ratio = over["exposed_bytes"] / base["exposed_bytes"]
    # the tentpole invariant: EXACTLY half the exposed bytes, total
    # wire bytes conserved, closed form agreeing with the measurement
    assert over["exposed_bytes"] * 2 == base["exposed_bytes"], legs
    assert over["total_bytes"] == base["total_bytes"], legs
    assert base["overlapped_bytes"] == 0, legs
    itemsize = 4
    for leg, flag in (("serialized", False), ("overlap", True)):
        want = ov.collective_bytes_per_layer(
            CFG.hidden_size, B * T, TP, itemsize=itemsize, overlap=flag
        )
        assert legs[leg]["exposed_bytes"] == want, (leg, want, legs[leg])

    # greedy byte-identity vs tp=1 (the serving property the engine
    # relies on; scripts/multichip_smoke.py gates the full engine path)
    kv1 = llama.init_kv_cache(CFG, 512, dtype=jnp.float32)
    ref_hidden, _ = llama.forward(
        params, CFG, jnp.asarray(tokens), jnp.asarray(positions), kv1,
        jnp.asarray(wslots.reshape(-1)), jnp.asarray(smat),
    )
    kv8 = llama.init_kv_cache(CFG, 512, dtype=jnp.float32)
    with compat.set_mesh(mesh):
        ov_hidden, _ = ov.tp_overlap_forward(
            params, CFG, jnp.asarray(tokens), jnp.asarray(positions), kv8,
            jnp.asarray(wslots.reshape(-1)), jnp.asarray(smat), mesh,
            page_size=8,
        )
    ref_tok = np.asarray(
        jnp.argmax(llama.logits(params, CFG, ref_hidden[:, -1]), -1)
    )
    ov_tok = np.asarray(
        jnp.argmax(llama.logits(params, CFG, ov_hidden[:, -1]), -1)
    )
    identical = bool(np.array_equal(ref_tok, ov_tok))
    assert identical, (ref_tok, ov_tok)

    # the production serving combination: pallas kernels + packed
    # quantized pools through the same executor, both KV tiers
    pallas_legs = {
        tier: _pallas_leg(tier, params, mesh) for tier in ("int8", "int4")
    }

    return {
        "devices": 8,
        "tp": TP,
        "model": CFG.name,
        "rows": B * T,
        "hidden_size": CFG.hidden_size,
        "dtype_itemsize": itemsize,
        "reps": REPS,
        "legs": legs,
        "exposed_ratio": ratio,            # the gated 0.5x invariant
        "total_bytes_conserved": True,
        "layer_step_overlap_speedup": round(
            base["layer_step_wall_s"] / over["layer_step_wall_s"], 4
        ),
        "greedy_byte_identical_vs_tp1": identical,
        "pallas_legs": pallas_legs,
        "note": (
            "CPU virtual devices run the rings sequentially: the wall "
            "delta is scheduling shape, not the TPU speedup; the gated "
            "invariants are the byte ledger and greedy byte-identity"
        ),
    }


if __name__ == "__main__":
    out = run()
    print(
        "tp_overlap: exposed_ratio={} wall serialized={}s overlap={}s "
        "identical={}".format(
            out["exposed_ratio"],
            out["legs"]["serialized"]["layer_step_wall_s"],
            out["legs"]["overlap"]["layer_step_wall_s"],
            out["greedy_byte_identical_vs_tp1"],
        ),
        file=sys.stderr,
    )
    for tier, leg in out["pallas_legs"].items():
        print(
            "tp_overlap pallas+{}: exposed_ratio={} wall overlap={}s "
            "fallback={}s identical={}".format(
                tier, leg["exposed_ratio"], leg["layer_step_wall_s"],
                leg["fallback_layer_step_wall_s"],
                leg["greedy_byte_identical_vs_tp1"],
            ),
            file=sys.stderr,
        )
    print(json.dumps(out))
