"""TP comm/compute overlap bench: serialized psums vs the ring executor.

Subprocess behind bench.py's `tp_overlap` BENCH_OUT section
(BENCH_TP_OVERLAP=1): bench.py initializes jax against the real
backend long before the section runs, and this bench needs its OWN
8-virtual-device CPU mesh — so it runs as a child process that forces
the platform before the jax import and prints ONE JSON line on stdout.

What it measures (parallel/tp_overlap.py, docs/parallelism.md):

- **Per-layer step wall, serialized vs overlapped** — the same
  `layer_step` under `single_layer_executor` with the two psums intact
  vs decomposed into ring reduce-scatter + matmul-fused all-gather
  (warmup + best-of-N). On virtual CPU devices the rings run
  sequentially, so this wall is a scheduling-shape datum, not a
  speedup claim — the TPU latency-hiding scheduler is what cashes the
  overlap in; the invariant CI gates on is the byte ledger.
- **Measured collective bytes** — `record_collectives()` armed around
  each leg's trace: exposed bytes (standalone collectives on the
  critical path) must read EXACTLY 0.5x the serialized leg's, total
  wire bytes must be conserved (RS+AG re-schedules traffic, it does
  not remove any), and both must match `collective_bytes_per_layer`'s
  closed form.
- **Greedy byte-identity** — `tp_overlap_forward` argmax tokens vs the
  tp=1 `llama.forward` (the FP reduction-order invariant the serving
  path relies on).

Run:  python scripts/tp_overlap_bench.py        (~1 min on CPU)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from dynamo_tpu import compat  # noqa: E402
from dynamo_tpu.models import config as cfgmod, llama  # noqa: E402
from dynamo_tpu.parallel import mesh as meshmod  # noqa: E402
from dynamo_tpu.parallel import tp_overlap as ov  # noqa: E402

TP = 8
B = int(os.environ.get("BENCH_TP_OVERLAP_B", "4"))
T = int(os.environ.get("BENCH_TP_OVERLAP_T", "16"))
REPS = int(os.environ.get("BENCH_TP_OVERLAP_REPS", "30"))

# tiny widened to 8 query + 8 kv heads so the head shards survive tp=8
# (the same shape the multichip smoke serves)
CFG = cfgmod.get_config("tiny").with_(
    dtype="float32", num_layers=2, num_heads=8, num_kv_heads=8
)


def _inputs(b, t, page=8):
    rng = np.random.RandomState(0)
    tokens = rng.randint(1, CFG.vocab_size, (b, t)).astype(np.int32)
    positions = np.tile(np.arange(t, dtype=np.int32), (b, 1))
    wslots = np.stack(
        [np.arange(page * (1 + 8 * i), page * (1 + 8 * i) + t) for i in range(b)]
    ).astype(np.int32)
    return tokens, positions, wslots, wslots.copy()


def run() -> dict:
    assert jax.device_count() == 8, jax.device_count()
    mesh = meshmod.build_mesh(meshmod.MeshConfig(tp=TP))
    tokens, positions, wslots, smat = _inputs(B, T)
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    x = np.asarray(params["embed"])[tokens].astype(np.float32)
    from dynamo_tpu.ops.rope import rope_cos_sin, rope_inv_freq

    cos, sin = rope_cos_sin(
        jnp.asarray(rope_inv_freq(CFG)), jnp.asarray(positions)
    )
    kv = llama.init_kv_cache(CFG, 512, dtype=jnp.float32)
    lp = params["layers"][0]
    args = (
        lp, kv.k[0], kv.v[0], jnp.asarray(x), cos, sin,
        jnp.asarray(wslots.reshape(-1)), jnp.asarray(smat),
        jnp.asarray(positions),
    )

    legs = {}
    for name, overlap in (("serialized", False), ("overlap", True)):
        step = ov.single_layer_executor(
            CFG, mesh, B, T, page_size=8, overlap=overlap
        )
        # arm the ledger around the TRACE (first call compiles): the
        # executor returns the overlap leg still scattered, so the
        # ledger sees exactly one layer's collectives — no amortization
        with ov.record_collectives() as led:
            jax.block_until_ready(step(*args))
        walls = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            jax.block_until_ready(step(*args))
            walls.append(time.perf_counter() - t0)
        legs[name] = {
            "layer_step_wall_s": round(min(walls), 6),
            "exposed_bytes": led.exposed,
            "overlapped_bytes": led.overlapped,
            "total_bytes": led.total,
        }

    base, over = legs["serialized"], legs["overlap"]
    ratio = over["exposed_bytes"] / base["exposed_bytes"]
    # the tentpole invariant: EXACTLY half the exposed bytes, total
    # wire bytes conserved, closed form agreeing with the measurement
    assert over["exposed_bytes"] * 2 == base["exposed_bytes"], legs
    assert over["total_bytes"] == base["total_bytes"], legs
    assert base["overlapped_bytes"] == 0, legs
    itemsize = 4
    for leg, flag in (("serialized", False), ("overlap", True)):
        want = ov.collective_bytes_per_layer(
            CFG.hidden_size, B * T, TP, itemsize=itemsize, overlap=flag
        )
        assert legs[leg]["exposed_bytes"] == want, (leg, want, legs[leg])

    # greedy byte-identity vs tp=1 (the serving property the engine
    # relies on; scripts/multichip_smoke.py gates the full engine path)
    kv1 = llama.init_kv_cache(CFG, 512, dtype=jnp.float32)
    ref_hidden, _ = llama.forward(
        params, CFG, jnp.asarray(tokens), jnp.asarray(positions), kv1,
        jnp.asarray(wslots.reshape(-1)), jnp.asarray(smat),
    )
    kv8 = llama.init_kv_cache(CFG, 512, dtype=jnp.float32)
    with compat.set_mesh(mesh):
        ov_hidden, _ = ov.tp_overlap_forward(
            params, CFG, jnp.asarray(tokens), jnp.asarray(positions), kv8,
            jnp.asarray(wslots.reshape(-1)), jnp.asarray(smat), mesh,
            page_size=8,
        )
    ref_tok = np.asarray(
        jnp.argmax(llama.logits(params, CFG, ref_hidden[:, -1]), -1)
    )
    ov_tok = np.asarray(
        jnp.argmax(llama.logits(params, CFG, ov_hidden[:, -1]), -1)
    )
    identical = bool(np.array_equal(ref_tok, ov_tok))
    assert identical, (ref_tok, ov_tok)

    return {
        "devices": 8,
        "tp": TP,
        "model": CFG.name,
        "rows": B * T,
        "hidden_size": CFG.hidden_size,
        "dtype_itemsize": itemsize,
        "reps": REPS,
        "legs": legs,
        "exposed_ratio": ratio,            # the gated 0.5x invariant
        "total_bytes_conserved": True,
        "layer_step_overlap_speedup": round(
            base["layer_step_wall_s"] / over["layer_step_wall_s"], 4
        ),
        "greedy_byte_identical_vs_tp1": identical,
        "note": (
            "CPU virtual devices run the rings sequentially: the wall "
            "delta is scheduling shape, not the TPU speedup; the gated "
            "invariants are the byte ledger and greedy byte-identity"
        ),
    }


if __name__ == "__main__":
    out = run()
    print(
        "tp_overlap: exposed_ratio={} wall serialized={}s overlap={}s "
        "identical={}".format(
            out["exposed_ratio"],
            out["legs"]["serialized"]["layer_step_wall_s"],
            out["legs"]["overlap"]["layer_step_wall_s"],
            out["greedy_byte_identical_vs_tp1"],
        ),
        file=sys.stderr,
    )
    print(json.dumps(out))
