"""Chaos-controller scenario: score the fleet control loop through a
worker death + load spike (docs/control.md "Proving the loop").

The scenario is the closed loop end to end, all real components:

    HubServer <- Supervisor/Watcher <- N x scripts/control_worker.py
        ^                                   (SloTracker + lease drain)
        |
    Planner (attainment-fed decide() + GraceGate) -> SupervisorConnector

Timeline (all durations configurable):

1. **warm**: base-rate open-loop load against the worker pool; fleet
   attainment settles at ~1.0;
2. **event**: the offered rate spikes past pool capacity AND the victim
   worker dies deterministically (``DYN_FAULTS=worker.die.fail@N`` — it
   hard-exits on its N-th request; the watcher's restart backoff keeps
   it dead for the scenario). Queueing delay blows through the TTFT
   target, the workers' rolling SLO windows burn, the fold's `min`
   drops below the planner target, and the planner scales the pool up
   (the KV threshold is parked unreachable, so scale-up is attributable
   to the ATTAINMENT path alone);
3. **recover**: base load continues; scored: time from the death until
   fleet min attainment returns to the pre-event level, and the
   SLO-attained goodput fraction retained through the episode;
4. **cooldown**: load drops near zero; attainment headroom + idle load
   lets the planner scale back down — scored: the drain was graceful
   (lease revoked BEFORE the process stopped, no SIGTERM escalation).

Emits one JSON dict (the ``control`` BENCH_OUT section); run directly
it prints the JSON and exits non-zero if the loop failed to close
(no scale-up, infinite recovery, or an ungraceful drain). Also
registered in the loadgen scenario registry as the ``control_chaos``
adapter (docs/loadgen.md), so ``scripts/run_scenarios.py --scenarios
all`` runs this proof too.

``--connector operator`` (or ``run_scenario(connector="operator")``)
drives the SAME scenario through the planner's OTHER scale connector:
the worker pool is deployed as a ``deploy/graphs/*`` spec
(scripts/control_graph.py) reconciled by the ``GraphOperator``, and
the planner scales by editing the spec in hub KV
(``OperatorConnector`` — the reference's planner-patches-CRD mode).
The recovery and revoke-before-stop drain contracts are asserted on
the reconciled watcher exactly as on the supervisor path.
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from dynamo_tpu.engine.kv_ledger import quiesce_census  # noqa: E402
from dynamo_tpu.llm.planner import (  # noqa: E402
    Planner,
    PlannerConfig,
    SupervisorConnector,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: E402
from dynamo_tpu.runtime.hub.server import HubServer  # noqa: E402
from dynamo_tpu.sdk.supervisor import Supervisor, Watcher  # noqa: E402
from dynamo_tpu.utils import counters  # noqa: E402

NS = "chaos"
COMPONENT = "backend"
WATCHER = "decoder"
WORKER_SCRIPT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "control_worker.py"
)


def _defaults() -> dict:
    """Tiny-scale defaults (CI smoke finishes in ~35 s on 2 cores)."""
    return dict(
        workers0=2,            # initial pool
        max_budget=4,          # planner chip budget (1 chip per replica)
        lanes=4,               # parallel lanes per worker
        service_s=0.08,        # per-request service time
        ttft_s=0.2,            # SLO target the tracker judges against
        base_rps=30.0,
        spike_rps=120.0,
        low_rps=4.0,
        warm_s=3.0,
        spike_s=5.0,
        recover_max_s=14.0,
        cooldown_max_s=16.0,
        die_at_hit=60,         # victim request count at death
        adjust_s=0.5,          # planner adjustment interval
    )


async def _load_phase(
    client, rate: float, duration: float, results: list, tasks: set
) -> None:
    """Open-loop arrivals at `rate` for `duration` seconds."""
    loop = asyncio.get_running_loop()
    end = loop.time() + duration

    async def one():
        t0 = loop.time()
        ok = True
        try:
            stream = await client.round_robin({"req": 1})
            async for _ in stream:
                pass
        except Exception:  # noqa: BLE001 — a failed request is honest
            # degradation data, not a harness error
            ok = False
        results.append((loop.time(), loop.time() - t0, ok))

    period = 1.0 / rate
    while loop.time() < end:
        t = asyncio.ensure_future(one())
        tasks.add(t)
        t.add_done_callback(tasks.discard)
        await asyncio.sleep(period)


def _attain_min(planner) -> float:
    att = planner.aggregator.attainment() if planner.aggregator else {}
    return min((v["min"] for v in att.values()), default=1.0)


async def run_scenario(connector: str = "supervisor", **overrides) -> dict:
    p = {**_defaults(), **overrides}
    hub = HubServer()
    await hub.start("127.0.0.1", 0)
    hub_addr = f"127.0.0.1:{hub.port}"

    worker_env = {
        "CHAOS_NS": NS,
        "CHAOS_COMPONENT": COMPONENT,
        "CHAOS_SERVICE_S": str(p["service_s"]),
        "CHAOS_LANES": str(p["lanes"]),
        "CHAOS_TTFT_S": str(p["ttft_s"]),
        "CHAOS_VICTIM": "0",
        # deterministic death: wid 0 exits on its N-th request
        "DYN_FAULTS": f"worker.die.fail@{p['die_at_hit']}",
        # keep jax (transitively imported) off any tunneled TPU
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
    }
    op = None
    hub_client = None
    if connector == "supervisor":
        sup = Supervisor(hub_addr=hub_addr)
        sup.watchers[WATCHER] = Watcher(
            name=WATCHER,
            args=[sys.executable, WORKER_SCRIPT],
            env=dict(worker_env),
            numprocesses=p["workers0"],
            # the dead victim must STAY dead for the scenario: recovery
            # is the planner's job here, not the restart loop's
            restart_backoff_s=120.0,
        )
        watcher = sup.watchers[WATCHER]
        await sup.start()
    elif connector == "operator":
        # the planner-patches-spec mode: deploy the SAME chaos pool as
        # a graph spec; the GraphOperator reconciles replica edits
        import json as _json

        from dynamo_tpu.runtime.hub.client import HubClient
        from dynamo_tpu.sdk.operator import GRAPH_PREFIX, GraphOperator

        graph_entry = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "control_graph.py"
        ) + ":ChaosDecoder"
        # DYN_LEASE_TTL: dead victims must vanish from discovery on the
        # recovery clock's timescale (control_worker.py pins its own)
        op = GraphOperator(
            hub_addr, extra_env={**worker_env, "DYN_LEASE_TTL": "1.5"}
        )
        await op.start()
        hub_client = await HubClient.connect(hub_addr)
        spec = {
            "entry": graph_entry,
            "services": {COMPONENT: {
                "workers": p["workers0"],
                "restart_backoff_s": 120.0,
            }},
        }
        await hub_client.kv_put(
            GRAPH_PREFIX + "chaos", _json.dumps(spec).encode()
        )
        for _ in range(200):
            if "chaos" in op.deployments:
                break
            await asyncio.sleep(0.05)
        if "chaos" not in op.deployments:
            raise RuntimeError("operator never reconciled the chaos spec")
        _, sup = op.deployments["chaos"]
        watcher = sup.watchers[COMPONENT]
    else:
        raise ValueError(f"unknown connector {connector!r}")

    observer = await DistributedRuntime.from_settings(hub_addr=hub_addr)
    client = await (
        observer.namespace(NS).component(COMPONENT).endpoint("generate").client()
    )
    await client.wait_for_instances()

    cfg = PlannerConfig(
        namespace=NS,
        decode_component=COMPONENT,
        disagg=False,
        metric_pull_interval_s=0.1,
        adjustment_interval_s=p["adjust_s"],
        min_endpoint=1,
        max_chip_budget=p["max_budget"],
        decode_engine_num_chips=1,
        # park the KV threshold unreachable: scale-up through THIS
        # scenario must come from the attainment path
        decode_kv_scale_up_threshold=1e9,
        decode_kv_scale_down_threshold=0.2,
        slo_attainment_target=0.99,
        scale_up_grace_rounds=0,
        scale_down_grace_rounds=2,
        # rounds are 0.5 s here: give a freshly spawned python worker
        # comfortably more than its ~1-2 s boot before its desired slot
        # reads as phantom (decay would re-add and overshoot the budget)
        desired_decay_rounds=8,
    )
    if connector == "supervisor":
        conn = SupervisorConnector(sup, {COMPONENT: WATCHER})
    else:
        from dynamo_tpu.sdk.operator import OperatorConnector

        conn = OperatorConnector(
            hub_client, "chaos", {COMPONENT: COMPONENT},
            max_replicas=p["max_budget"],
        )
    planner = Planner(observer, conn, cfg)
    ups0 = counters.get("planner_scale_up_total")
    downs0 = counters.get("planner_scale_down_total")
    await planner.start()

    loop = asyncio.get_running_loop()
    t0 = loop.time()
    results: list[tuple[float, float, bool]] = []
    tasks: set = set()
    timeline: list[dict] = []
    stop_sampling = asyncio.Event()

    async def sampler():
        while not stop_sampling.is_set():
            timeline.append(
                {
                    "t": round(loop.time() - t0, 2),
                    "attain_min": round(_attain_min(planner), 4),
                    "alive": watcher.alive_count(),
                    "procs": watcher.numprocesses,
                    "decision": (
                        planner.last_decision.reason
                        if planner.last_decision else ""
                    ),
                }
            )
            await asyncio.sleep(0.25)

    sampler_task = asyncio.create_task(sampler())

    # -- phase 1: warm ---------------------------------------------------
    await _load_phase(client, p["base_rps"], p["warm_s"], results, tasks)

    # -- phase 2: spike (the victim dies mid-spike via DYN_FAULTS) -------
    alive_before = watcher.alive_count()
    spike_start = loop.time() - t0
    await _load_phase(client, p["spike_rps"], p["spike_s"], results, tasks)

    # death time: first sample where the live count dropped
    t_death = next(
        (s["t"] for s in timeline
         if s["t"] >= spike_start and s["alive"] < alive_before),
        spike_start,
    )

    # -- phase 3: recover at base load, until attainment heals -----------
    pre = [
        s["attain_min"] for s in timeline
        if spike_start - 2.0 <= s["t"] < spike_start
    ]
    pre_attain = round(statistics.fmean(pre), 4) if pre else 1.0
    recover_level = min(pre_attain, cfg.slo_attainment_target)
    t_recovered = None
    deadline = loop.time() + p["recover_max_s"]
    while loop.time() < deadline:
        await _load_phase(client, p["base_rps"], 0.5, results, tasks)
        now_t = loop.time() - t0
        if now_t > t_death and _attain_min(planner) >= recover_level:
            t_recovered = now_t
            break

    # -- phase 4: cooldown: near-idle load -> scale-down + drain ---------
    peak_procs = max(s["procs"] for s in timeline)
    drain_deadline = loop.time() + p["cooldown_max_s"]
    while loop.time() < drain_deadline:
        await _load_phase(client, p["low_rps"], 0.5, results, tasks)
        if watcher.numprocesses < peak_procs and any(
            e[0] == "drained" for e in watcher.events
        ):
            break

    if tasks:
        await asyncio.gather(*list(tasks), return_exceptions=True)
    stop_sampling.set()
    await sampler_task
    await planner.stop()
    drain_events = list(watcher.events)
    await observer.shutdown()
    if op is not None:
        await op.stop()  # tears down the reconciled supervisor
    else:
        await sup.stop()
    if hub_client is not None:
        await hub_client.close()
    await hub.stop()

    # ---------------------------------------------------------------- score
    def frac_attained(lo: float, hi: float) -> float:
        win = [
            (ok and lat <= p["ttft_s"])
            for (t, lat, ok) in results
            if lo <= t - t0 < hi
        ]
        return round(sum(win) / len(win), 4) if win else 1.0

    pre_frac = frac_attained(0.0, spike_start)
    event_end = (t_recovered if t_recovered is not None
                 else spike_start + p["spike_s"] + p["recover_max_s"])
    event_frac = frac_attained(t_death, event_end)
    drained_wids = [w for (e, w) in drain_events if e == "drained"]
    drain_clean = bool(drained_wids) and all(
        # revoke must precede the drained exit, with no SIGTERM escalation
        ("lease_revoked", w) in drain_events
        and drain_events.index(("lease_revoked", w))
        < drain_events.index(("drained", w))
        and ("sigterm", w) not in drain_events
        for w in drained_wids
    )
    post = [s["attain_min"] for s in timeline[-4:]]
    return {
        "scenario": {
            "connector": connector,
            "workers_initial": p["workers0"],
            "chip_budget": p["max_budget"],
            "base_rps": p["base_rps"],
            "spike_rps": p["spike_rps"],
            "faults": f"worker.die.fail@{p['die_at_hit']}",
            "ttft_target_s": p["ttft_s"],
        },
        "event": {
            "t_spike_s": round(spike_start, 2),
            "t_death_s": round(t_death, 2),
        },
        "attainment": {
            "pre": pre_attain,
            "floor_during": round(
                min(
                    (s["attain_min"] for s in timeline if s["t"] >= t_death),
                    default=1.0,
                ), 4,
            ),
            "post": round(statistics.fmean(post), 4) if post else None,
            "target": cfg.slo_attainment_target,
        },
        "time_to_recover_s": (
            round(t_recovered - t_death, 2) if t_recovered is not None else None
        ),
        "goodput": {
            "pre_frac": pre_frac,
            "event_frac": event_frac,
            "retained": (
                round(event_frac / pre_frac, 4) if pre_frac else None
            ),
        },
        "scaling": {
            "ups": int(counters.get("planner_scale_up_total") - ups0),
            "downs": int(counters.get("planner_scale_down_total") - downs0),
            # chips are held by RUNNING processes: the dead victim's
            # watcher slot stays in `procs` (it would restart after the
            # scenario) but its chip is free — the budget metric is the
            # peak LIVE count
            "peak_alive": max(s["alive"] for s in timeline),
            "peak_slots": peak_procs,
            "final_workers": watcher.numprocesses,
        },
        "drain": {"clean": drain_clean, "events": drain_events},
        # workers are subprocess Sim engines — no in-process paged KV —
        # so the quiesce census is the honest degenerate one (zero
        # engines, zero orphans); any in-process ledger would be scored
        "kv_census": await asyncio.to_thread(quiesce_census, []),
        "requests": len(results),
        "timeline": timeline,
    }


def run(**overrides) -> dict:
    return asyncio.run(run_scenario(**overrides))


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--connector", default="supervisor",
        choices=["supervisor", "operator"],
        help="planner scale connector: direct Supervisor calls, or "
             "spec edits reconciled by the GraphOperator",
    )
    args = ap.parse_args(argv)
    out = run(connector=args.connector)
    print(json.dumps(out, indent=2))
    ok = (
        out["scaling"]["ups"] >= 1
        and out["time_to_recover_s"] is not None
        and out["drain"]["clean"]
        and out["kv_census"]["ok"]
    )
    if not ok:
        print(
            f"control loop FAILED to close ({args.connector} connector)",
            file=sys.stderr,
        )
        return 1
    print(
        f"control loop closed ({args.connector} connector): recovered in "
        f"{out['time_to_recover_s']}s, goodput retained "
        f"{out['goodput']['retained']}, drain clean", file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
