"""Ablation profile of one decode step at serving batch size.

Tunnel-aware: chain N donated dispatches, fetch one element once (see
profile_decode.py docstring). Run: python scripts/profile_ablate.py [B]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import get_config
from dynamo_tpu.ops.sampling import sample_tokens

B = int(sys.argv[1]) if len(sys.argv) > 1 else 64
CFG = get_config("llama-3.2-1b")
PAGE = 16
MAX_LEN = 608
W = -(-MAX_LEN // PAGE)
NUM_SLOTS = (B * W + 17) * PAGE
DTYPE = jnp.bfloat16


def chain_kv(name, fn, kv, n=10):
    kv = fn(kv)
    _ = np.asarray(jax.tree.leaves(kv)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(n):
        kv = fn(kv)
    _ = np.asarray(jax.tree.leaves(kv)[0].ravel()[:1])
    dt = (time.perf_counter() - t0) / n
    print(f"{name:52s} {dt*1000:9.2f} ms", flush=True)
    return kv, dt


def main():
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=DTYPE)
    kv = jax.device_put(llama.init_kv_cache(CFG, NUM_SLOTS, dtype=DTYPE))

    tables_np = np.stack([np.arange(1 + i * W, 1 + (i + 1) * W) for i in range(B)])
    tables = jnp.asarray(tables_np, jnp.int32)
    tokens = jnp.ones((B,), jnp.int32)
    positions = jnp.full((B,), 500, jnp.int32)
    lengths = jnp.full((B,), 501, jnp.int32)
    wpos = jnp.full((B,), 500, jnp.int32)
    temp = jnp.zeros((B,), jnp.float32)
    topk = jnp.zeros((B,), jnp.int32)
    topp = jnp.ones((B,), jnp.float32)
    key = jax.random.PRNGKey(0)
    wslots = (
        jnp.take_along_axis(tables, (positions // PAGE)[:, None], axis=1)[:, 0]
        * PAGE + positions % PAGE
    ).astype(jnp.int32)
    smat = (tables[:, :, None] * PAGE + jnp.arange(PAGE, dtype=jnp.int32)).reshape(B, -1)

    def mk_step(spec, with_logits=True, with_attn=True):
        def step(params, kv):
            hidden, kv = llama.forward(
                params, CFG, tokens[:, None], positions[:, None], kv, wslots, spec
            )
            if with_logits:
                lg = llama.logits(params, CFG, hidden[:, 0])
                toks = sample_tokens(lg, key, temp, topk, topp)
            else:
                toks = jnp.sum(hidden)
            return toks, kv
        j = jax.jit(step, donate_argnums=(1,))
        return lambda kv: j(params, kv)[1]

    spec_g = llama.AttnSpec.gather(smat)
    spec_f = llama.AttnSpec.pallas_decode(tables, lengths, PAGE, write_pos=wpos)

    kv, _ = chain_kv("full step gather", mk_step(spec_g), kv)
    kv, _ = chain_kv("full step fused-pallas (ppb=8)", mk_step(spec_f), kv)
    kv, _ = chain_kv("gather step, no logits/sampling", mk_step(spec_g, with_logits=False), kv)

    # attention+write fully ablated (keeps qkv/mlp/norm weights streaming)
    import dynamo_tpu.ops.attention as A
    real_write, real_attn = A.write_kv_slots, A.paged_attention
    llama_write, llama_attn = llama.write_kv_slots, llama.paged_attention
    try:
        A.write_kv_slots = lambda kc, vc, s, nk, nv: (kc, vc)
        llama.write_kv_slots = A.write_kv_slots
        fake = lambda q, kc, vc, sm, pos: q
        A.paged_attention = fake
        llama.paged_attention = fake
        kv, _ = chain_kv("step, attention+write ablated", mk_step(spec_g), kv)
        kv, _ = chain_kv("step, attn+write+logits ablated",
                         mk_step(spec_g, with_logits=False), kv)
    finally:
        A.write_kv_slots, A.paged_attention = real_write, real_attn
        llama.write_kv_slots, llama.paged_attention = llama_write, llama_attn

    # pallas kernel ppb variants, standalone chained via q feedback
    from dynamo_tpu.ops.pallas_attention import fused_paged_decode_attention

    for ppb in (4, 8, 16, 32):
        if W % ppb and ppb > W:
            continue
        q0 = jnp.ones((B, CFG.num_heads, CFG.head_dim), DTYPE)
        nk = jnp.ones((B, CFG.num_kv_heads, CFG.head_dim), DTYPE)

        def attn_only(q, kvk, kvv):
            o, kvk, kvv = fused_paged_decode_attention(
                q, nk, nk, kvk, kvv, tables, lengths, wpos,
                page_size=PAGE, pages_per_block=ppb)
            return o, kvk, kvv

        j = jax.jit(attn_only, donate_argnums=(1, 2))
        kk, vv = kv.k[0], kv.v[0]
        q, kk, vv = j(q0, kk, vv)
        _ = np.asarray(q[0, 0, :1])
        t0 = time.perf_counter()
        for _ in range(20):
            q, kk, vv = j(q, kk, vv)
        _ = np.asarray(q[0, 0, :1])
        t = (time.perf_counter() - t0) / 20
        kv_read = B * 501 * CFG.num_kv_heads * CFG.head_dim * 2 * 2
        print(f"{'fused kernel alone ppb=%d' % ppb:52s} {t*1000:9.2f} ms"
              f"  ({kv_read/t/1e9:6.1f} GB/s, x{CFG.num_layers} = {t*1000*CFG.num_layers:6.1f} ms)",
              flush=True)


if __name__ == "__main__":
    main()
