"""In-scan ablation: time the engine-shaped 16-step decode scan with
components knocked out, at serving batch. The scan amortizes dispatch
overhead so numbers are stable through the tunnel.
Run: python scripts/profile_scan_ablate.py [B]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import get_config
from dynamo_tpu.ops.sampling import sample_tokens

B = int(sys.argv[1]) if len(sys.argv) > 1 else 64
CFG = get_config("llama-3.2-1b")
PAGE = int(os.environ.get("PROF_PAGE", "16"))
MAX_LEN = 640
W = -(-MAX_LEN // PAGE)
NUM_SLOTS = (B * W + 17) * PAGE
DTYPE = jnp.bfloat16
STEPS = 16


def scan_step(mode, with_logits, with_attn, ppb=8):
    tables_np = np.stack([np.arange(1 + i * W, 1 + (i + 1) * W) for i in range(B)])
    tables = jnp.asarray(tables_np, jnp.int32)
    temp = jnp.zeros((B,), jnp.float32)
    topk = jnp.zeros((B,), jnp.int32)
    topp = jnp.ones((B,), jnp.float32)
    smat = (tables[:, :, None] * PAGE + jnp.arange(PAGE, dtype=jnp.int32)).reshape(B, -1)

    def multi(params, kv, tokens, positions, key):
        def body(carry, _):
            tokens, positions, kv, key = carry
            key, sub = jax.random.split(key)
            wslots = (
                jnp.take_along_axis(tables, (positions // PAGE)[:, None], axis=1)[:, 0]
                * PAGE + positions % PAGE
            ).astype(jnp.int32)
            if mode == "fused":
                spec = llama.AttnSpec.pallas_decode(
                    tables, positions + 1, PAGE, write_pos=positions)
                spec.pages_per_block = ppb
            else:
                spec = llama.AttnSpec.gather(smat)
            hidden, kv = llama.forward(
                params, CFG, tokens[:, None], positions[:, None], kv, wslots, spec
            )
            if with_logits:
                lg = llama.logits(params, CFG, hidden[:, 0])
                toks = sample_tokens(lg, sub, temp, topk, topp)
            else:
                toks = tokens
            return (toks, positions + 1, kv, key), toks

        (_, _, kv, _), out = jax.lax.scan(
            body, (tokens, positions, kv, key), None, length=STEPS)
        return out, kv

    return multi


def run(name, mode, with_logits=True, with_attn=True, n=6):
    import dynamo_tpu.ops.attention as A
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=DTYPE)
    kv = jax.device_put(llama.init_kv_cache(CFG, NUM_SLOTS, dtype=DTYPE))
    tokens = jnp.ones((B,), jnp.int32)
    positions = jnp.full((B,), 480, jnp.int32)
    key = jax.random.PRNGKey(0)

    real_write, real_attn = A.write_kv_slots, A.paged_attention
    lw, la = llama.write_kv_slots, llama.paged_attention
    try:
        if not with_attn:
            A.write_kv_slots = lambda kc, vc, s, nk, nv: (kc, vc)
            llama.write_kv_slots = A.write_kv_slots
            fake = lambda q, kc, vc, sm, pos: q
            A.paged_attention = fake
            llama.paged_attention = fake
        f = jax.jit(scan_step(mode, with_logits, with_attn), donate_argnums=(1,))
        out, kv = f(params, kv, tokens, positions, key)
        _ = np.asarray(out[-1, :1])
        t0 = time.perf_counter()
        for _ in range(n):
            out, kv = f(params, kv, tokens, positions, key)
        _ = np.asarray(out[-1, :1])
        dt = (time.perf_counter() - t0) / n / STEPS
        print(f"{name:55s} {dt*1000:8.2f} ms/step  ({B/dt:8.0f} tok/s)", flush=True)
    finally:
        A.write_kv_slots, A.paged_attention = real_write, real_attn
        llama.write_kv_slots, llama.paged_attention = lw, la
    del params, kv


if __name__ == "__main__":
    print(f"B={B}")
    run("gather full", "gather")
    run("gather, no attention/write", "gather", with_attn=False)
    run("gather, no logits/sampling", "gather", with_logits=False)
    run("no attn, no logits (weights floor)", "gather", with_logits=False, with_attn=False)
    run("fused-pallas full", "fused")
