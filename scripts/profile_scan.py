"""Ablation profile of the 16-step decode scan: where do the ms/token go?

Run: python scripts/profile_scan.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import get_config
from dynamo_tpu.ops import attention as attn
from dynamo_tpu.ops.sampling import sample_tokens

CFG = get_config("llama-3.2-1b")
PAGE = 16
B = 8
MAX_LEN = 608
W = -(-MAX_LEN // PAGE)
NUM_SLOTS = (B * W + 17) * PAGE
DTYPE = jnp.bfloat16
STEPS = 16


def timeit(name, fn, *args, n=3, **kw):
    jax.block_until_ready(fn(*args, **kw))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:50s} {dt*1000:9.2f} ms  ({dt*1000/STEPS:6.2f} /tok)")
    return dt


def make_scan(sample_mode="full", attn_mode="gather", logits_mode="full"):
    temp = jnp.zeros((B,), jnp.float32)
    topk = jnp.zeros((B,), jnp.int32)
    topp = jnp.ones((B,), jnp.float32)

    def decode_multi(params, kv, tokens, positions, tables, key):
        s = PAGE
        smat = (tables[:, :, None] * s + jnp.arange(s, dtype=jnp.int32)).reshape(B, -1)

        def body(carry, _):
            tokens, positions, kv, key = carry
            key, sub = jax.random.split(key)
            page_idx = jnp.minimum(positions // s, W - 1)
            wslots = (
                jnp.take_along_axis(tables, page_idx[:, None], axis=1)[:, 0] * s
                + positions % s
            )
            wslots = jnp.where(positions < MAX_LEN, wslots, 0).astype(jnp.int32)

            real_paged = attn.paged_attention
            if attn_mode == "none":
                attn.paged_attention = lambda q, kc, vc, sm, pos: q
                llama.paged_attention = attn.paged_attention
            try:
                hidden, kv2 = llama.forward(
                    params, CFG, tokens[:, None], positions[:, None], kv, wslots, smat
                )
            finally:
                attn.paged_attention = real_paged
                llama.paged_attention = real_paged

            if logits_mode == "full":
                lg = llama.logits(params, CFG, hidden[:, 0])
            else:
                lg = hidden[:, 0, : 128].astype(jnp.float32)  # skip vocab matmul

            if sample_mode == "full":
                toks = sample_tokens(lg, sub, temp, topk, topp)
            else:
                toks = jnp.argmax(lg, -1).astype(jnp.int32)
            return (toks, positions + 1, kv2, key), toks

        (_, _, kv, _), out = jax.lax.scan(
            body, (tokens, positions, kv, key), None, length=STEPS
        )
        return out, kv

    return jax.jit(decode_multi, donate_argnums=(1,))


def main():
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=DTYPE)
    tables = np.stack([np.arange(1 + i * W, 1 + (i + 1) * W) for i in range(B)])
    tables = jnp.asarray(tables, jnp.int32)
    tokens = jnp.ones((B,), jnp.int32)
    positions = jnp.full((B,), 500, jnp.int32)
    key = jax.random.PRNGKey(0)

    def fresh_kv():
        return jax.device_put(llama.init_kv_cache(CFG, NUM_SLOTS, dtype=DTYPE))

    for name, kw in [
        ("full (baseline)", {}),
        ("greedy-only sampling", dict(sample_mode="greedy")),
        ("no attention", dict(attn_mode="none")),
        ("no vocab logits+greedy", dict(logits_mode="none", sample_mode="greedy")),
        ("no attn + no vocab + greedy",
         dict(attn_mode="none", logits_mode="none", sample_mode="greedy")),
    ]:
        fn = make_scan(**kw)
        kv = fresh_kv()
        fn(params, kv, tokens, positions, tables, key)  # compile (donates kv)
        kv = fresh_kv()
        jax.block_until_ready(kv)
        t0 = time.perf_counter()
        out, kv = fn(params, kv, tokens, positions, tables, key)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        print(f"{name:50s} {dt*1000:9.2f} ms  ({dt*1000/STEPS:6.2f} /tok)")


if __name__ == "__main__":
    main()
