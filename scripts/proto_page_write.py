"""Prototype: Pallas page-scatter write kernel vs XLA row scatter.
Writes [n_pages, PAGE, KW] source blocks into a [NUM_PAGES, PAGE, KW]
pool view at scalar-prefetched page ids, aliased in-place.
Run: python scripts/proto_page_write.py
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PAGE = 64
KW = 512
N = 64
T = 512
W = 10
NUM_PAGES = N * W + 17
NUM_SLOTS = NUM_PAGES * PAGE
L = 16
REPS = 4


def _kernel(tbl_ref, kp_ref, vp_ref, src_k_ref, src_v_ref, ok_ref, ov_ref):
    del kp_ref, vp_ref  # aliased through; only the indexed blocks change
    ok_ref[...] = src_k_ref[...]
    ov_ref[...] = src_v_ref[...]


@functools.partial(jax.jit, donate_argnums=(0, 1))
def pallas_page_write(k_cache, v_cache, tables, new_k, new_v):
    """k_cache/v_cache [NUM_SLOTS, KW]; tables [n_pages] page ids;
    new_k/new_v [n_pages, PAGE, KW]."""
    n_pages = tables.shape[0]
    kp = k_cache.reshape(NUM_PAGES, PAGE, KW)
    vp = v_cache.reshape(NUM_PAGES, PAGE, KW)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_pages,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((1, PAGE, KW), lambda i, tbl: (i, 0, 0)),
            pl.BlockSpec((1, PAGE, KW), lambda i, tbl: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, PAGE, KW), lambda i, tbl: (tbl[i], 0, 0)),
            pl.BlockSpec((1, PAGE, KW), lambda i, tbl: (tbl[i], 0, 0)),
        ],
    )
    ok, ov = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(kp.shape, kp.dtype),
            jax.ShapeDtypeStruct(vp.shape, vp.dtype),
        ],
        input_output_aliases={1: 0, 2: 1},  # (after scalar) kp->ok, vp->ov
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
    )(tables, kp, vp, new_k, new_v)
    return ok.reshape(NUM_SLOTS, KW), ov.reshape(NUM_SLOTS, KW)


def main():
    rng = np.random.RandomState(0)
    kc = jnp.asarray(rng.randn(NUM_SLOTS, KW), jnp.bfloat16)
    vc = jnp.asarray(rng.randn(NUM_SLOTS, KW), jnp.bfloat16)
    n_full = T // PAGE
    tables_np = np.concatenate(
        [np.arange(1 + i * W, 1 + i * W + n_full) for i in range(N)]
    ).astype(np.int32)
    tables = jnp.asarray(tables_np)
    src_k = jnp.asarray(rng.randn(N * n_full, PAGE, KW), jnp.bfloat16)
    src_v = jnp.asarray(rng.randn(N * n_full, PAGE, KW), jnp.bfloat16)

    # correctness (on copies: the write donates its pool inputs)
    kc_host = np.asarray(kc)
    ok, ov = pallas_page_write(
        jnp.asarray(kc_host), jnp.array(vc), tables, src_k, src_v
    )
    ref_pages = kc_host.copy().reshape(NUM_PAGES, PAGE, KW)
    ref_pages[tables_np] = np.asarray(src_k)
    got = np.asarray(ok).reshape(NUM_PAGES, PAGE, KW)
    assert np.array_equal(got, ref_pages), "write mismatch"
    print("correctness ok")
    kc2 = jnp.asarray(kc_host)
    vc2 = jnp.array(vc)

    # speed: L chained writes (kernel)
    @jax.jit
    def many_pallas(kc, vc, tables, sk, sv):
        def body(carry, _):
            kc, vc = carry
            kc, vc = pallas_page_write(kc, vc, tables, sk, sv)
            return (kc, vc), kc[0, 0]
        (kc, vc), o = jax.lax.scan(body, (kc, vc), None, length=L)
        return o, kc, vc

    o, kc3, vc3 = many_pallas(kc2, vc2, tables, src_k, src_v)
    _ = np.asarray(o[-1])
    t0 = time.perf_counter()
    for _ in range(REPS):
        o, kc3, vc3 = many_pallas(kc3, vc3, tables, src_k, src_v)
    _ = np.asarray(o[-1])
    dt = (time.perf_counter() - t0) / REPS / L
    gb = 2 * N * n_full * PAGE * KW * 2 / 1e9
    print(f"pallas page write: {dt * 1e3:.3f} ms/layer "
          f"({gb / dt:.0f} GB/s) vs XLA row scatter ~24.5 ms/layer")


if __name__ == "__main__":
    main()
