"""Chaos-controller graph entry: the control_worker.py behavior as a
``@service`` class, deployable through the GraphOperator
(``deploy/graphs/*`` spec -> Supervisor.for_graph -> sdk/worker.py).

scripts/control_chaos.py uses this for its ``--connector operator``
leg: the planner scales by editing the deployment spec in hub KV
(``OperatorConnector``), the operator reconciles it into the live
watcher, and the SAME drain/recovery contract proven for the
SupervisorConnector path is asserted on the reconciled processes.

Behavior (mirrors scripts/control_worker.py):

- each request occupies one of ``CHAOS_LANES`` parallel lanes for
  ``CHAOS_SERVICE_S`` seconds, so lost capacity produces real queueing
  delay;
- a rolling `SloTracker` judges every request against the
  ``CHAOS_TTFT_S`` target and rides the stats replies via the sdk
  worker's ``dynamo_stats_handler`` hook — the planner's attainment
  input;
- the designated victim (``CHAOS_VICTIM`` == worker id) consults the
  ``worker.die`` fault point per request and hard-exits when it fires
  (``DYN_FAULTS=worker.die.fail@N``);
- the lease-revoke graceful-drain contract comes free from
  sdk/worker.py (DYN_WATCHER_NAME is stamped by the Watcher).
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from dynamo_tpu.llm.http.metrics import SloTracker  # noqa: E402
from dynamo_tpu.sdk import endpoint, service  # noqa: E402
from dynamo_tpu.utils import faults  # noqa: E402

NS = os.environ.get("CHAOS_NS", "chaos")
COMPONENT = os.environ.get("CHAOS_COMPONENT", "backend")


@service(name=COMPONENT, namespace=NS)
class ChaosDecoder:
    def __init__(self):
        self.worker_id = int(self.dynamo_context.get("worker_id", 0))
        self.victim = self.worker_id == int(
            os.environ.get("CHAOS_VICTIM", "-1")
        )
        self.service_s = float(os.environ.get("CHAOS_SERVICE_S", "0.04"))
        self.lanes_n = int(os.environ.get("CHAOS_LANES", "4"))
        self.slo = SloTracker(
            {"default": {
                "ttft_s": float(os.environ.get("CHAOS_TTFT_S", "0.2"))
            }},
            window_s=float(os.environ.get("CHAOS_SLO_WINDOW_S", "3.0")),
        )
        self.lanes = asyncio.Semaphore(self.lanes_n)
        self.state = {"waiting": 0, "active": 0, "served": 0}

    @endpoint()
    async def generate(self, request):
        if self.victim:
            # deterministic death: DYN_FAULTS=worker.die.fail@N (the
            # data-plane server armed the registry via load_env)
            try:
                faults.fire("worker.die")
            except faults.FaultError:
                os._exit(1)
        t0 = time.monotonic()
        state, slo, lanes = self.state, self.slo, self.lanes
        service_s, wid = self.service_s, self.worker_id

        async def stream():
            state["waiting"] += 1
            async with lanes:
                state["waiting"] -= 1
                state["active"] += 1
                try:
                    await asyncio.sleep(service_s)
                finally:
                    state["active"] -= 1
            lat = time.monotonic() - t0
            state["served"] += 1
            slo.observe({"tenant": "default", "ttft_s": lat})
            yield {"ttft_s": round(lat, 5), "worker": wid}

        return stream()

    def dynamo_stats_handler(self) -> dict:
        return {
            "request_active_slots": self.state["active"],
            "request_total_slots": self.lanes_n,
            "num_requests_waiting": self.state["waiting"],
            "gpu_cache_usage_perc": self.state["active"] / self.lanes_n,
            "slo_attainment": self.slo.snapshot(),
        }
