"""Raw Pallas page-DMA microbenchmark: how fast can one program stream
scattered pages HBM->VMEM at varying buffer depth and page size?
Bounds the paged-attention kernel. Run: python scripts/profile_dma.py
"""

import functools
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def make_bench(num_pages_total, page, kw, n_pages, nbuf, dtype):
    def kernel(tables_ref, pages_hbm, out_ref, bufs, sems):
        # prologue: fill the pipeline
        for j in range(nbuf):
            pltpu.make_async_copy(
                pages_hbm.at[tables_ref[j]], bufs.at[j], sems.at[j]
            ).start()

        def body(i, acc):
            slot = jax.lax.rem(i, nbuf)
            pltpu.make_async_copy(
                pages_hbm.at[0], bufs.at[slot], sems.at[slot]
            ).wait()
            # touch the buffer so the copy isn't dead
            acc = acc + jnp.sum(bufs[slot, 0].astype(jnp.float32)) * 0.0
            nxt = i + nbuf

            @pl.when(nxt < n_pages)
            def _():
                pltpu.make_async_copy(
                    pages_hbm.at[tables_ref[nxt]], bufs.at[slot], sems.at[slot]
                ).start()

            return acc

        acc = jax.lax.fori_loop(0, n_pages, body, 0.0)
        out_ref[0, 0] = acc

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        scratch_shapes=[
            pltpu.VMEM((nbuf, page, kw), dtype),
            pltpu.SemaphoreType.DMA((nbuf,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
    )


def main():
    kw = 512
    dtype = jnp.bfloat16
    rng = np.random.RandomState(0)
    for page in (16, 64, 128, 256):
        total_pages = (1 << 24) // (page * kw * 2)  # 16MB pool? use 512MB
        total_pages = max(total_pages, 4096)
        pool = jnp.zeros((total_pages, page, kw), dtype)
        n_pages = min(total_pages, (64 * 1024 * 1024) // (page * kw * 2))  # stream 64MB
        for nbuf in (2, 4, 8, 16):
            tables = jnp.asarray(
                rng.permutation(total_pages)[:n_pages], jnp.int32
            )
            bench = make_bench(total_pages, page, kw, n_pages, nbuf, dtype)
            f = jax.jit(lambda t, p: bench(t, p))
            o = f(tables, pool)
            _ = np.asarray(o)
            t0 = time.perf_counter()
            for _ in range(10):
                o = f(tables, pool)
            _ = np.asarray(o)
            t = (time.perf_counter() - t0) / 10
            data = n_pages * page * kw * 2
            print(
                f"page={page:4d} ({page*kw*2//1024:4d}KB) nbuf={nbuf:3d}: "
                f"{t*1000:7.2f} ms for {data>>20} MB -> {data/t/1e9:7.1f} GB/s",
                flush=True,
            )


if __name__ == "__main__":
    main()
