"""Profile the engine's real _decode_fn across batch sizes and backends.

Tunnel-aware methodology (the bench chip sits behind an RPC tunnel with
~120 ms fetch RTT, ~1.4 ms per-dispatch overhead, and a block_until_ready
that does NOT wait for execution): chain N donated dispatches and fetch one
element once, so per-iter = compute + dispatch overhead and the RTT
amortizes away. Run: python scripts/profile_decode.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine import EngineConfig, JaxEngine

ISL, OSL = 512, 64


def time_decode(engine: JaxEngine, n=10):
    cfg = engine.config
    b = cfg.max_batch_size
    w = cfg.max_pages_per_seq
    tables = np.stack([np.arange(1 + i * w, 1 + (i + 1) * w) for i in range(b)])
    args = (
        jnp.ones((b,), jnp.int32),
        jnp.full((b,), ISL, jnp.int32),
        jnp.asarray(tables, jnp.int32),
        jnp.ones((b,), bool),
        jnp.zeros((b,), jnp.float32),
        jnp.zeros((b,), jnp.int32),
        jnp.ones((b,), jnp.float32),
        jax.random.PRNGKey(0),
    )
    kv = engine.kv
    out, kv = engine._decode_fn(engine.params, kv, *args, True)
    _ = np.asarray(out[-1, :1])  # force warmup completion
    t0 = time.perf_counter()
    for _ in range(n):
        out, kv = engine._decode_fn(engine.params, kv, *args, True)
    _ = np.asarray(out[-1, :1])
    dt = (time.perf_counter() - t0) / n
    engine.kv = kv
    return dt


def main():
    for backend in ("pallas", "gather"):
        for b in (8, 32, 64, 128):
            eng = JaxEngine(
                EngineConfig(
                    model="llama-3.2-1b",
                    dtype="bfloat16",
                    page_size=64,
                    max_batch_size=b,
                    max_model_len=ISL + OSL + 32,
                    prefill_chunk=ISL,
                    decode_steps=16,
                    attn_backend=backend,
                )
            )
            try:
                dt = time_decode(eng)
                per_tok = dt / eng.config.decode_steps
                print(
                    f"backend={backend:7s} B={b:4d}  dispatch={dt*1000:8.2f} ms  "
                    f"per-step={per_tok*1000:7.2f} ms  "
                    f"toks/s={b/per_tok:10.1f}",
                    flush=True,
                )
            finally:
                del eng


if __name__ == "__main__":
    main()
