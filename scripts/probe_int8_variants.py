"""Attention-only decode-kernel variant shootout at serving shapes.

Round-4 left int8-KV decode ~1.8 ms/step short of its byte-count ideal
at B=256 (KERNEL_TPU.json). Candidate causes: int8 (32,128) VMEM-tile
DMA penalty vs the scale-tile DMAs doubling the copy count. This times
the REAL kernel (chained scan, donated pools — axon methodology) per
variant and ablation to attribute the loss before committing to the
int32-packing refactor.

Run: python scripts/probe_int8_variants.py [B] [kv_len]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.ops.pallas_attention import fused_paged_decode_attention

B = int(sys.argv[1]) if len(sys.argv) > 1 else 256
KV_LEN = int(sys.argv[2]) if len(sys.argv) > 2 else 480
STEPS = 16
PAGE = 128
KH, HD, H = 8, 64, 32
KW = KH * HD


def time_variant(name, quant, ablate="", iters=3):
    w = -(-(KV_LEN + STEPS + PAGE) // PAGE)
    num_pages = B * w + 17
    num_slots = num_pages * PAGE
    rng = np.random.RandomState(0)
    tables = jnp.asarray(
        np.stack([np.arange(1 + i * w, 1 + (i + 1) * w) for i in range(B)]),
        jnp.int32,
    )

    if quant:
        from dynamo_tpu.ops.quant import init_kv_scale_pool

        k_cache = jnp.asarray(
            rng.randint(-127, 128, size=(num_slots, KW)), jnp.int8
        )
        v_cache = jnp.asarray(
            rng.randint(-127, 128, size=(num_slots, KW)), jnp.int8
        )
        ks = init_kv_scale_pool(num_pages, PAGE, KH)
        vs = init_kv_scale_pool(num_pages, PAGE, KH)
        subl = ks.shape[1]
    else:
        k_cache = jnp.asarray(rng.randn(num_slots, KW), jnp.bfloat16)
        v_cache = jnp.asarray(rng.randn(num_slots, KW), jnp.bfloat16)

    q = jnp.asarray(rng.randn(B, H, HD), jnp.bfloat16)

    def multi(q, k_cache, v_cache, *scales):
        def body(carry, i):
            if quant:
                k_cache, v_cache, ks, vs = carry
            else:
                k_cache, v_cache = carry
            positions = jnp.full((B,), KV_LEN, jnp.int32) + i
            args = dict(
                page_size=PAGE, ablate=ablate,
            )
            if quant:
                nk = jnp.ones((B, KW), jnp.int8)
                nv = jnp.ones((B, KW), jnp.int8)
                nks = jnp.ones((B, subl), jnp.float32)
                nvs = jnp.ones((B, subl), jnp.float32)
                out, k_cache, v_cache, ks, vs = fused_paged_decode_attention(
                    q, nk, nv, k_cache, v_cache, tables, positions + 1,
                    positions, ks, vs, nks, nvs, **args,
                )
                carry = (k_cache, v_cache, ks, vs)
            else:
                nk = jnp.ones((B, KW), jnp.bfloat16)
                nv = jnp.ones((B, KW), jnp.bfloat16)
                out, k_cache, v_cache = fused_paged_decode_attention(
                    q, nk, nv, k_cache, v_cache, tables, positions + 1,
                    positions, **args,
                )
                carry = (k_cache, v_cache)
            return carry, out[0, 0, 0]

        init = (k_cache, v_cache, *scales) if quant else (k_cache, v_cache)
        carry, outs = jax.lax.scan(
            body, init, jnp.arange(STEPS, dtype=jnp.int32)
        )
        return outs[-1]

    f = jax.jit(multi, donate_argnums=(1, 2, 3, 4) if quant else (1, 2))
    args = (q, k_cache, v_cache) + ((ks, vs) if quant else ())
    _ = np.asarray(f(*args))
    best = None
    for _ in range(iters):
        t0 = time.perf_counter()
        _ = np.asarray(f(*args))
        dt = (time.perf_counter() - t0) / STEPS
        best = dt if best is None else min(best, dt)
    # streamed bytes per step: every live page's K+V (+scale tiles)
    live_pages = int(np.sum(-(-(np.full(B, KV_LEN + 1)) // PAGE)))
    nbytes = live_pages * PAGE * KW * 2 * k_cache.dtype.itemsize
    if quant:
        nbytes += live_pages * subl * PAGE * 4 * 2
    print(
        f"{name:32s} {best * 1e3:7.2f} ms/step   {nbytes / best / 1e9:6.0f} GB/s"
    )
    return best


def main():
    print(f"B={B} kv_len={KV_LEN} page={PAGE} 1B dims (kh=8 hd=64)")
    time_variant("bf16", quant=False)
    time_variant("int8+scales", quant=True)
    time_variant("int8 noscale_dma", quant=True, ablate="noscale_dma")
    time_variant("int8 noscale_mul", quant=True, ablate="noscale_mul")


if __name__ == "__main__":
    main()
