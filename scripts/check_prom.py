"""Prometheus text-format checker for the /metrics exposition.

CI's answer to "the scrape regressed silently": validates that

- every sample's metric family has a ``# TYPE`` declaration (histogram
  samples resolve through their ``_bucket``/``_sum``/``_count`` suffixes);
- no series (name + label set) appears twice — duplicate series make
  Prometheus drop the scrape;
- declared families actually expose at least one sample (the zero-series
  rule: a family that is declared but renders nothing is invisible to
  rate() from the first scrape);
- histogram families carry ``+Inf`` bucket, ``_sum`` and ``_count``.

Usage:
    python scripts/check_prom.py <file>     # validate a saved scrape
    python scripts/check_prom.py --spawn    # start a real HttpService
                                            # (echo model + tiny engine
                                            # metrics + SLO tracker +
                                            # health counters), GET
                                            # /metrics over HTTP, then
                                            # validate the body
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})?\s+(?P<value>[^\s]+)$"
)
_SUFFIXES = ("_bucket", "_sum", "_count")


def _family(name: str, types: dict) -> str:
    """Resolve a sample name to its declared family (histogram samples
    carry suffixes the TYPE line does not)."""
    if name in types:
        return name
    for suf in _SUFFIXES:
        if name.endswith(suf) and name[: -len(suf)] in types:
            return name[: -len(suf)]
    return name


def validate(text: str) -> list[str]:
    """Returns a list of problems (empty = clean)."""
    errors: list[str] = []
    types: dict[str, str] = {}
    seen_series: set[str] = set()
    samples_per_family: dict[str, int] = {}
    hist_parts: dict[str, set] = {}

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                "counter", "gauge", "histogram", "summary", "untyped"
            ):
                errors.append(f"line {lineno}: malformed TYPE line: {line!r}")
                continue
            if parts[2] in types:
                # the real Prometheus text parser rejects ANY second
                # TYPE line for a name (even a consistent one) and
                # drops the whole scrape — so do we
                errors.append(
                    f"line {lineno}: duplicate TYPE for {parts[2]} "
                    f"(Prometheus rejects re-declared families)"
                )
                continue
            types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            errors.append(f"line {lineno}: unparseable sample: {line!r}")
            continue
        name = m.group("name")
        fam = _family(name, types)
        if fam not in types:
            errors.append(f"line {lineno}: sample {name} has no # TYPE")
        series = name + (m.group("labels") or "")
        if series in seen_series:
            errors.append(f"line {lineno}: duplicate series: {series}")
        seen_series.add(series)
        samples_per_family[fam] = samples_per_family.get(fam, 0) + 1
        if types.get(fam) == "histogram":
            parts = hist_parts.setdefault(fam, set())
            if name.endswith("_sum"):
                parts.add("sum")
            elif name.endswith("_count"):
                parts.add("count")
            elif name.endswith("_bucket") and 'le="+Inf"' in (
                m.group("labels") or ""
            ):
                parts.add("inf")
        try:
            float(m.group("value"))
        except ValueError:
            errors.append(f"line {lineno}: non-numeric value: {line!r}")

    # zero-series rule: every declared family exposes >= 1 sample
    for fam in types:
        if samples_per_family.get(fam, 0) == 0:
            errors.append(f"family {fam} declared but renders no samples")
    for fam, parts in hist_parts.items():
        missing = {"sum", "count", "inf"} - parts
        if missing:
            errors.append(f"histogram {fam} missing {sorted(missing)}")
    return errors


async def _spawn_and_scrape() -> str:
    """Serve a real /metrics (HttpService + engine metrics + SLO tracker
    + health counters), scrape it over HTTP, return the body."""
    import aiohttp

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.engines import EchoEngineFull
    from dynamo_tpu.llm.http.metrics import EngineMetrics, SloTracker
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.models import config as cfgmod
    from dynamo_tpu.utils import instance
    from dynamo_tpu.utils.counters import PromCounters

    engine = JaxEngine(
        EngineConfig(
            model=cfgmod.get_config("tiny"), dtype="float32",
            page_size=8, num_pages=64, max_batch_size=4,
            max_model_len=128, prefill_chunk=32, seed=0,
        )
    )
    slo = SloTracker({"default": {"ttft_s": 2.0, "itl_s": 0.1,
                                  "queue_wait_s": 1.0}})
    # one synthetic finished request so attainment windows carry samples
    slo.observe({"tenant": "default", "ttft_s": 0.5, "itl_s": 0.01,
                 "queue_wait_s": 0.2})
    svc = HttpService()
    svc.manager.add_chat_model("echo", EchoEngineFull())
    svc.metrics.extra.append(PromCounters())
    svc.metrics.extra.append(
        EngineMetrics(engine, slo=slo, worker_id=instance.worker_id())
    )
    await svc.start("127.0.0.1", 0)
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{svc.port}/metrics"
            ) as resp:
                assert resp.status == 200, resp.status
                return await resp.text()
    finally:
        await svc.stop()
        await engine.close()


# families the forensics plane must render with zero-series from the
# FIRST scrape of a live engine (declared at registration — an engine
# that drops the declarations would pass the generic checks by simply
# not rendering them, so --spawn pins them by name)
REQUIRED_SPAWN_FAMILIES = (
    "dynamo_tpu_engine_step_anomalies_total",
    "dynamo_tpu_flight_recorder_dumps_total",
    "dynamo_tpu_flight_recorder_suppressed_total",
    "dynamo_tpu_profiler_captures_total",
    "dynamo_tpu_engine_flight_digests",
    "dynamo_tpu_kv_ledger_transitions_total",
    "dynamo_tpu_kv_ledger_violations_total",
    "dynamo_tpu_kv_ledger_audits_total",
)


def main(argv: list[str]) -> int:
    spawned = bool(argv) and argv[0] == "--spawn"
    if spawned:
        import asyncio

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        text = asyncio.run(_spawn_and_scrape())
    elif argv:
        with open(argv[0]) as f:
            text = f.read()
    else:
        text = sys.stdin.read()
    errors = validate(text)
    if spawned:
        declared = {
            line.split()[2] for line in text.splitlines()
            if line.startswith("# TYPE ") and len(line.split()) >= 3
        }
        for fam in REQUIRED_SPAWN_FAMILIES:
            if fam not in declared:
                errors.append(
                    f"required family {fam} missing from a live scrape"
                )
    families = len([ln for ln in text.splitlines()
                    if ln.startswith("# TYPE ")])
    if errors:
        for e in errors:
            print(f"check_prom: {e}", file=sys.stderr)
        print(f"check_prom: FAILED ({len(errors)} problems, "
              f"{families} families)", file=sys.stderr)
        return 1
    print(f"check_prom ok: {families} families, "
          f"{len(text.splitlines())} lines")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
