"""Paced-arrival A/B probe: does the admission batching window recover
the offered load? Runs the 1B engine (fast init) with Poisson arrivals
at a fraction of its closed-loop rate and reports delivered throughput +
client/engine TTFT, with and without the window.

Run: python scripts/probe_paced.py [frac] [n_requests]
"""

import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

FRAC = float(sys.argv[1]) if len(sys.argv) > 1 else 0.35
N_REQ = int(sys.argv[2]) if len(sys.argv) > 2 else 96
ISL, OSL = 512, 64
CONC = 128


def build_engine(window):
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import get_config

    return JaxEngine(EngineConfig(
        model=get_config("llama-3.2-1b"),
        dtype="bfloat16",
        max_batch_size=CONC,
        max_model_len=ISL + OSL + 32,
        prefill_chunk=ISL,
        decode_steps=16,
        prefill_group_tokens=32768,
        quantization="int8",
        kv_quantization="int8",
        page_size=128,
        prefill_batch_window_s=window,
    ))


async def drive(engine, cfg_vocab, rng):
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.pipeline.context import Context

    async def one(prompt, record):
        pre = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=OSL, ignore_eos=True),
            sampling_options=SamplingOptions(greedy=True),
        )
        t0 = time.perf_counter()
        ticks = []
        async for frame in await engine.generate(Context(pre.to_dict())):
            if frame.get("token_ids"):
                ticks.append(time.perf_counter())
            meta = frame.get("meta")
            if meta and "engine_ttft_s" in meta:
                record["engine_ttft"] = meta["engine_ttft_s"]
                record["queue_wait"] = meta.get("queue_wait_s")
        record["ttft"] = ticks[0] - t0
        record["tokens"] = len(ticks)

    def prompts(n):
        return [rng.randint(1, cfg_vocab, size=ISL).tolist() for _ in range(n)]

    # warmup: full wave x2 + small families + a second full wave
    for _ in range(2):
        await asyncio.gather(*(one(p, {}) for p in prompts(CONC)))
    for k in (1, 2, 3, 6, 12, 24, 48):
        await asyncio.gather(*(one(p, {}) for p in prompts(k)))
    # closed-loop rate
    recs = [dict() for _ in range(CONC)]
    t0 = time.perf_counter()
    await asyncio.gather(*(one(p, r) for p, r in zip(prompts(CONC), recs)))
    wall = time.perf_counter() - t0
    closed_rate = CONC / wall
    closed_toks = sum(r["tokens"] for r in recs) / wall

    # paced
    ps0 = engine.phase_stats
    rate = FRAC * closed_rate
    gaps = rng.exponential(1.0 / rate, size=N_REQ)
    precs = [dict() for _ in range(N_REQ)]
    tasks = []
    tp0 = time.perf_counter()
    for i, p in enumerate(prompts(N_REQ)):
        tasks.append(asyncio.create_task(one(p, precs[i])))
        await asyncio.sleep(float(gaps[i]))
    await asyncio.gather(*tasks)
    paced_wall = time.perf_counter() - tp0
    ps1 = engine.phase_stats
    print("  paced phase deltas:",
          {k: round(ps1[k] - ps0[k], 3) for k in ps0}, flush=True)
    print(f"  paced_wall {paced_wall:.2f}s", flush=True)
    return dict(
        closed_rate=closed_rate,
        closed_toks=closed_toks,
        offered_rate=rate,
        offered_toks=rate * OSL,
        paced_toks=sum(r["tokens"] for r in precs) / paced_wall,
        p50_ttft=float(np.percentile([r["ttft"] for r in precs], 50)),
        p95_ttft=float(np.percentile([r["ttft"] for r in precs], 95)),
        p50_engine_ttft=float(np.percentile(
            [r["engine_ttft"] for r in precs if r.get("engine_ttft")], 50
        )),
        p50_queue_wait=float(np.percentile(
            [r["queue_wait"] for r in precs if r.get("queue_wait") is not None], 50
        )),
    )


def main():
    from dynamo_tpu.models.config import get_config

    vocab = get_config("llama-3.2-1b").vocab_size
    for window in (0.0, 0.25):
        rng = np.random.RandomState(0)
        engine = build_engine(window)
        out = asyncio.run(drive(engine, vocab, rng))
        asyncio.run(engine.close())
        del engine
        print(f"window={window}:")
        for k, v in out.items():
            print(f"  {k:18s} {v:8.2f}")
        sys.stdout.flush()


if __name__ == "__main__":
    main()
