"""Train the vendored tiny-Llama checkpoint (tests/data/tiny-trained-llama).

The zero-egress sandbox cannot download a trained model, so the trained-
checkpoint test trains its own: a 2-layer Llama-architecture model fit
to convergence on a small templated factual corpus with this repo's own
stack (llama.forward on CPU + optax), then exported in HF format
(config.json + model.safetensors + tokenizer.json) so the full
LocalModel -> load_params -> engine path runs on LEARNED weights.
Counterpart of the reference's checked-in sample models
(lib/llm/tests/data/sample-models/TinyLlama_v1.1).

Run: JAX_PLATFORMS=cpu python scripts/train_tiny_checkpoint.py
(~2 min on one CPU core; writes ~1.5 MB of safetensors)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "data", "tiny-trained-llama",
)

CAPITALS = {
    "france": "paris", "germany": "berlin", "italy": "rome",
    "spain": "madrid", "japan": "tokyo", "china": "beijing",
    "russia": "moscow", "egypt": "cairo", "canada": "ottawa",
    "brazil": "brasilia", "india": "delhi", "greece": "athens",
    "norway": "oslo", "kenya": "nairobi", "peru": "lima",
    "austria": "vienna", "poland": "warsaw", "ireland": "dublin",
}
COLORS = {
    "sky": "blue", "grass": "green", "snow": "white", "coal": "black",
    "blood": "red", "sun": "yellow",
}


def build_corpus() -> str:
    lines = []
    for c, cap in CAPITALS.items():
        lines.append(f"the capital of {c} is {cap} .")
        lines.append(f"{cap} is the capital of {c} .")
    for thing, color in COLORS.items():
        lines.append(f"the color of the {thing} is {color} .")
        lines.append(f"the {thing} is {color} .")
    for a in range(1, 6):
        for b in range(1, 6):
            lines.append(f"{a} plus {b} is {a + b} .")
    # repeat for a few epochs' worth of contiguous text
    return " ".join(lines * 8)


def build_tokenizer(corpus: str):
    from tokenizers import Tokenizer, models, normalizers, pre_tokenizers, trainers

    tok = Tokenizer(models.WordLevel(unk_token="<unk>"))
    tok.normalizer = normalizers.Lowercase()
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = trainers.WordLevelTrainer(
        special_tokens=["<unk>", "<s>", "</s>"]
    )
    tok.train_from_iterator([corpus], trainer)
    return tok


def main() -> None:
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig

    corpus = build_corpus()
    tok = build_tokenizer(corpus)
    vocab = tok.get_vocab_size()
    print(f"corpus {len(corpus)} chars, vocab {vocab}")

    cfg = ModelConfig(
        name="tiny-trained-llama",
        vocab_size=vocab,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        max_position_embeddings=256,
        tie_word_embeddings=True,
        dtype="float32",
    )
    ids = np.asarray(tok.encode(corpus, add_special_tokens=False).ids)
    T = 64
    n_seq = len(ids) // (T + 1)
    data = ids[: n_seq * (T + 1)].reshape(n_seq, T + 1)
    print(f"{n_seq} training sequences of {T} tokens")

    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)

    import optax

    opt = optax.adam(3e-3)
    opt_state = opt.init(params)
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32), (8, 1))

    def loss_fn(params, batch):
        x, y = batch[:, :-1], batch[:, 1:]
        b, t = x.shape
        num_slots = b * t + 8
        kv = llama.init_kv_cache(cfg, num_slots, dtype=jnp.float32)
        wslots = (jnp.arange(b * t) + 8).astype(jnp.int32)
        smat = jnp.concatenate(
            [wslots.reshape(b, t), jnp.zeros((b, 8), jnp.int32)], axis=1
        )
        hidden, _ = llama.forward(
            params, cfg, x, positions[:b], kv, wslots, smat
        )
        logits = llama.logits(params, cfg, hidden.reshape(b * t, -1))
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, y.reshape(-1)[:, None], axis=-1
        )
        return jnp.mean(nll)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.RandomState(0)
    steps = int(os.environ.get("TRAIN_STEPS", "1200"))
    for i in range(steps):
        rows = rng.randint(0, n_seq, size=8)
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(data[rows])
        )
        if i % 100 == 0 or i == steps - 1:
            print(f"step {i:5d} loss {float(loss):.4f}", flush=True)
    final_loss = float(loss)

    # quick greedy sanity through the raw forward
    probe = tok.encode("the capital of france is", add_special_tokens=False).ids
    x = jnp.asarray([probe])
    kv = llama.init_kv_cache(cfg, len(probe) + 16, dtype=jnp.float32)
    wslots = (jnp.arange(len(probe)) + 1).astype(jnp.int32)
    smat = jnp.asarray([list(range(1, len(probe) + 1)) + [0] * 4])
    hidden, _ = llama.forward(
        params, cfg, x, jnp.arange(len(probe))[None], kv, wslots, smat
    )
    nxt = int(jnp.argmax(llama.logits(params, cfg, hidden[:, -1])[0]))
    print("'the capital of france is' ->", tok.decode([nxt]))

    # ---- export HF-format checkpoint -----------------------------------
    os.makedirs(OUT, exist_ok=True)
    from safetensors.numpy import save_file

    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    ours_to_hf = {
        "attn_norm": ("input_layernorm.weight", False),
        "wq": ("self_attn.q_proj.weight", True),
        "wk": ("self_attn.k_proj.weight", True),
        "wv": ("self_attn.v_proj.weight", True),
        "wo": ("self_attn.o_proj.weight", True),
        "mlp_norm": ("post_attention_layernorm.weight", False),
        "w_gate": ("mlp.gate_proj.weight", True),
        "w_up": ("mlp.up_proj.weight", True),
        "w_down": ("mlp.down_proj.weight", True),
    }
    for i, lp in enumerate(params["layers"]):
        for ours, (hf_name, transpose) in ours_to_hf.items():
            arr = np.asarray(lp[ours], np.float32)
            if transpose:
                arr = np.ascontiguousarray(arr.T)  # ours [in,out] -> HF [out,in]
            tensors[f"model.layers.{i}.{hf_name}"] = arr
    save_file(tensors, os.path.join(OUT, "model.safetensors"))

    with open(os.path.join(OUT, "config.json"), "w") as f:
        json.dump(
            {
                "model_type": "llama",
                "vocab_size": cfg.vocab_size,
                "hidden_size": cfg.hidden_size,
                "intermediate_size": cfg.intermediate_size,
                "num_hidden_layers": cfg.num_layers,
                "num_attention_heads": cfg.num_heads,
                "num_key_value_heads": cfg.num_kv_heads,
                "head_dim": cfg.head_dim,
                "rope_theta": cfg.rope_theta,
                "rms_norm_eps": cfg.rms_norm_eps,
                "max_position_embeddings": cfg.max_position_embeddings,
                "tie_word_embeddings": True,
                "torch_dtype": "float32",
                "training": {
                    "final_loss": round(final_loss, 4),
                    "steps": steps,
                    "corpus_chars": len(corpus),
                },
            },
            f,
            indent=1,
        )
    tok.save(os.path.join(OUT, "tokenizer.json"))
    with open(os.path.join(OUT, "tokenizer_config.json"), "w") as f:
        json.dump({"tokenizer_class": "PreTrainedTokenizerFast"}, f)
    total = sum(
        os.path.getsize(os.path.join(OUT, p)) for p in os.listdir(OUT)
    )
    print(f"wrote {OUT} ({total / 1e6:.2f} MB)")


if __name__ == "__main__":
    main()
