"""Compiled-mode validation + microbenchmark of the fused paged-decode
kernel on the real TPU chip (interpret-mode CPU tests cannot validate DMA/
semaphore semantics or VMEM sizing — this runs the Mosaic-compiled kernel).

Writes KERNEL_TPU.json at the repo root:
  { "backend", "agree_max_err", "configs": [ {B, pages, GB/s, ms}, ... ] }

Timing methodology: through the axon tunnel, standalone dispatch timing
carries a fixed ~11 ms artifact and block_until_ready does not block —
so each config is timed as N chained kernel calls (each consuming the
previous pool) ended by a value fetch, the same in-scan methodology the
decode profiles use.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.ops.pallas_attention import fused_paged_decode_attention


def oracle(q, k_cache, v_cache, tables, lengths, page_size):
    b, h, hd = q.shape
    kw = k_cache.shape[1]
    kh = kw // hd
    g = h // kh
    smat = (tables[:, :, None] * page_size + np.arange(page_size)).reshape(b, -1)
    out = np.zeros((b, h, hd), np.float32)
    qf = np.asarray(q, np.float32)
    for i in range(b):
        n = int(lengths[i])
        if n == 0:
            continue
        slots = smat[i, :n]
        k = np.asarray(k_cache, np.float32)[slots].reshape(n, kh, hd)
        v = np.asarray(v_cache, np.float32)[slots].reshape(n, kh, hd)
        for head in range(h):
            kh_i = head // g
            s = (qf[i, head] @ k[:, kh_i].T) / np.sqrt(hd)
            p = np.exp(s - s.max())
            p /= p.sum()
            out[i, head] = p @ v[:, kh_i]
    return out


def main() -> None:
    backend = jax.default_backend()
    record: dict = {"backend": backend, "configs": []}
    if backend != "tpu":
        print(json.dumps({"error": f"no TPU (backend={backend})"}))
        return

    rng = np.random.RandomState(0)
    page, hd, kh, h = 64, 64, 8, 32
    kw = kh * hd

    # ---- correctness: compiled kernel vs numpy oracle ----------------
    b, w = 8, 8
    num_pages = 128
    k_cache = rng.randn(num_pages * page, kw).astype(np.float32)
    v_cache = rng.randn(num_pages * page, kw).astype(np.float32)
    q = rng.randn(b, h, hd).astype(np.float32)
    tables = rng.permutation(num_pages - 1)[: b * w].reshape(b, w) + 1
    lengths = rng.randint(1, w * page, size=b).astype(np.int32)
    ref = oracle(q, k_cache, v_cache, tables, lengths, page)
    out, _, _ = jax.jit(
        lambda *a: fused_paged_decode_attention(
            *a, jnp.full((b,), -1, jnp.int32), page_size=page, alias_caches=False
        )
    )(
        jnp.asarray(q), jnp.zeros((b, kw), jnp.float32),
        jnp.zeros((b, kw), jnp.float32),
        jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(tables, jnp.int32), jnp.asarray(lengths),
    )
    err = float(np.abs(np.asarray(out) - ref).max())
    record["agree_max_err"] = err
    assert err < 2e-2, f"compiled kernel disagrees with oracle: {err}"
    print(f"compiled-mode agreement: max err {err:.2e}")

    # ---- int8-KV compiled agreement: quantized pools + scale tiles ----
    # (page 128: the scale-pool layout puts page tokens in lanes)
    from dynamo_tpu.ops.quant import (
        dequantize_kv_rows,
        gather_kv_scales,
        init_kv_scale_pool,
        quantize_kv_rows,
        scatter_kv_scales,
    )

    qpage = 128
    qnum_pages = 64
    qn_slots = qnum_pages * qpage
    kq, ksd = quantize_kv_rows(jnp.asarray(rng.randn(qn_slots, kw), jnp.float32), kh)
    vq, vsd = quantize_kv_rows(jnp.asarray(rng.randn(qn_slots, kw), jnp.float32), kh)
    all_slots = jnp.arange(qn_slots, dtype=jnp.int32)
    ks = scatter_kv_scales(
        init_kv_scale_pool(qnum_pages, qpage, kh), all_slots, ksd, kh)
    vs = scatter_kv_scales(
        init_kv_scale_pool(qnum_pages, qpage, kh), all_slots, vsd, kh)
    subl = ks.shape[1]
    qw = 4
    qtables = rng.permutation(qnum_pages - 1)[: b * qw].reshape(b, qw) + 1
    qlengths = rng.randint(1, qw * qpage, size=b).astype(np.int32)
    ref_q = oracle(
        q, np.asarray(dequantize_kv_rows(kq, ksd)),
        np.asarray(dequantize_kv_rows(vq, vsd)), qtables, qlengths, qpage,
    )
    out_q, *_ = jax.jit(
        lambda *a: fused_paged_decode_attention(
            *a, page_size=qpage, alias_caches=False
        )
    )(
        jnp.asarray(q),
        jnp.zeros((b, kw), jnp.int8), jnp.zeros((b, kw), jnp.int8),
        kq, vq,
        jnp.asarray(qtables, jnp.int32), jnp.asarray(qlengths),
        jnp.full((b,), -1, jnp.int32),
        ks, vs,
        jnp.ones((b, subl), jnp.float32), jnp.ones((b, subl), jnp.float32),
    )
    err_q = float(np.abs(np.asarray(out_q) - ref_q).max())
    record["agree_max_err_int8kv"] = err_q
    assert err_q < 2e-2, f"int8-KV kernel disagrees with oracle: {err_q}"
    print(f"int8-KV compiled-mode agreement: max err {err_q:.2e}")

    # ---- int32-PACKED pools: compiled kernel must be bit-identical ----
    from dynamo_tpu.ops.quant import pack_kv_slots

    out_p, *_ = jax.jit(
        lambda *a: fused_paged_decode_attention(
            *a, page_size=qpage, alias_caches=False
        )
    )(
        jnp.asarray(q),
        jnp.zeros((b, kw), jnp.int8), jnp.zeros((b, kw), jnp.int8),
        pack_kv_slots(kq), pack_kv_slots(vq),
        jnp.asarray(qtables, jnp.int32), jnp.asarray(qlengths),
        jnp.full((b,), -1, jnp.int32),
        ks, vs,
        jnp.ones((b, subl), jnp.float32), jnp.ones((b, subl), jnp.float32),
    )
    err_p = float(np.abs(np.asarray(out_p) - np.asarray(out_q)).max())
    record["packed_vs_dense_max_err"] = err_p
    assert err_p == 0.0, f"packed kernel differs from dense-int8: {err_p}"
    print(f"packed-pool compiled-mode agreement: bit-identical to dense")
    del kq, vq, ks, vs

    # ---- bandwidth: engine-shaped 16-layer decode scan, attention cost
    # isolated by ablation (fused-full minus attention-knocked-out) —
    # the only methodology that is stable through the tunnel (standalone
    # single-kernel timing carries a fixed ~11 ms dispatch artifact)
    import dynamo_tpu.ops.attention as A
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import get_config
    from dynamo_tpu.ops.sampling import sample_tokens

    cfg = get_config("llama-3.2-1b")
    dtype = jnp.bfloat16
    steps_n = 16
    kv_len = 480

    def time_scan(b, with_attn, quant=False, kv_quant=False, packed=False):
        # int8-KV scale pages put tokens in lanes -> page must be a lane
        # multiple; bf16 runs keep the serving default
        pg = 128 if kv_quant else page
        w_pages = -(-(kv_len + steps_n + pg) // pg)
        num_slots = (b * w_pages + 17) * pg
        tables = jnp.asarray(
            np.stack([np.arange(1 + i * w_pages, 1 + (i + 1) * w_pages)
                      for i in range(b)]), jnp.int32)
        temp = jnp.zeros((b,), jnp.float32)
        topk = jnp.zeros((b,), jnp.int32)
        topp = jnp.ones((b,), jnp.float32)

        def multi(params, kv, tokens, positions, key):
            def body(carry, _):
                tokens, positions, kv, key = carry
                key, sub = jax.random.split(key)
                wslots = (
                    jnp.take_along_axis(
                        tables, (positions // pg)[:, None], axis=1
                    )[:, 0] * pg + positions % pg
                ).astype(jnp.int32)
                spec = llama.AttnSpec.pallas_decode(
                    tables, positions + 1, pg, write_pos=positions
                )
                hidden, kv = llama.forward(
                    params, cfg, tokens[:, None], positions[:, None],
                    kv, wslots, spec,
                )
                lg = llama.logits(params, cfg, hidden[:, 0])
                toks = sample_tokens(lg, sub, temp, topk, topp, all_greedy=True)
                return (toks, positions + 1, kv, key), toks

            (_, _, kv, _), out = jax.lax.scan(
                body, (tokens, positions, kv, key), None, length=steps_n)
            return out, kv

        params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
        if quant:
            from dynamo_tpu.ops.quant import quantize_params

            params = quantize_params(params, cfg)
        kv = jax.device_put(llama.init_kv_cache(
            cfg, num_slots, dtype=dtype,
            kv_quant="int8" if kv_quant else None, page_size=pg,
            packed=packed,
        ))
        tokens = jnp.ones((b,), jnp.int32)
        positions = jnp.full((b,), kv_len, jnp.int32)
        key = jax.random.PRNGKey(0)
        real = (A.write_kv_slots, llama.write_kv_slots,
                llama.fused_paged_decode_attention
                if hasattr(llama, "fused_paged_decode_attention") else None)
        try:
            if not with_attn:
                import dynamo_tpu.ops.pallas_attention as PA

                real_fused = PA.fused_paged_decode_attention
                PA_fake = lambda q, nk, nv, kc, vc, *a, **kw: (q, kc, vc)
                PA.fused_paged_decode_attention = PA_fake
            f = jax.jit(multi, donate_argnums=(1,))
            out, kv = f(params, kv, tokens, positions, key)
            _ = np.asarray(out[-1, :1])
            t0 = time.perf_counter()
            n = 6
            for _ in range(n):
                out, kv = f(params, kv, tokens, positions, key)
            _ = np.asarray(out[-1, :1])
            return (time.perf_counter() - t0) / n / steps_n
        finally:
            if not with_attn:
                PA.fused_paged_decode_attention = real_fused
            del params, kv

    for b in (64, 128, 256):
        full = time_scan(b, with_attn=True)
        no_attn = time_scan(b, with_attn=False)
        full_q = time_scan(b, with_attn=True, quant=True)
        full_qq = time_scan(
            b, with_attn=True, quant=True, kv_quant=True, packed=True
        )
        attn_ms = (full - no_attn) * 1e3
        kv_bytes = b * kv_len * kw * 2 * 2 * cfg.num_layers  # K+V bf16, 16 L
        gbps = kv_bytes / max(full - no_attn, 1e-9) / 1e9
        record["configs"].append(
            {
                "B": b, "kv_len": kv_len, "page": page,
                "full_ms_per_step": round(full * 1e3, 3),
                "attn_ms_per_step": round(attn_ms, 3),
                "attn_GBps": round(gbps, 1),
                "decode_toks_per_s": round(b / full, 0),
                # int8 W8A8 weights (ops/quant.py), attention still bf16
                "full_ms_per_step_int8": round(full_q * 1e3, 3),
                "decode_toks_per_s_int8": round(b / full_q, 0),
                # int8 weights + int8 KV pages (the full quantized stack)
                "full_ms_per_step_int8kv": round(full_qq * 1e3, 3),
                "decode_toks_per_s_int8kv": round(b / full_qq, 0),
            }
        )
        print(f"B={b}: full {full * 1e3:.2f} ms/step, attention "
              f"{attn_ms:.2f} ms -> {gbps:.0f} GB/s, {b / full:.0f} tok/s; "
              f"int8 {full_q * 1e3:.2f} ms -> {b / full_q:.0f} tok/s; "
              f"int8+int8kv {full_qq * 1e3:.2f} ms -> {b / full_qq:.0f} tok/s")

    # ---- flash prefill kernel: compiled agreement + chunk-batch rate --
    from dynamo_tpu.ops.attention import slots_from_pages
    from dynamo_tpu.ops.pallas_prefill import flash_prefill_attention

    b, t_len, w = 8, 512, 10
    num_pages = b * w + 2
    kcf = rng.randn(num_pages * page, kw).astype(np.float32)
    vcf = rng.randn(num_pages * page, kw).astype(np.float32)
    qf3 = rng.randn(b, t_len, h, hd).astype(np.float32)
    tablesf = np.stack(
        [np.arange(1 + i * w, 1 + (i + 1) * w) for i in range(b)]
    ).astype(np.int32)
    pos0 = np.zeros(b, np.int32)
    tlen = np.full(b, t_len, np.int32)
    outf = flash_prefill_attention(
        jnp.asarray(qf3), jnp.asarray(kcf), jnp.asarray(vcf),
        jnp.asarray(tablesf), jnp.asarray(pos0), jnp.asarray(tlen),
        page_size=page,
    )
    from dynamo_tpu.ops.attention import paged_attention

    smat = np.asarray(slots_from_pages(jnp.asarray(tablesf), page))
    reff = np.asarray(paged_attention(
        jnp.asarray(qf3), jnp.asarray(kcf), jnp.asarray(vcf),
        jnp.asarray(smat),
        jnp.asarray(np.tile(np.arange(t_len), (b, 1)), jnp.int32),
    ))
    perr = float(np.abs(np.asarray(outf) - reff).max())
    record["prefill_agree_max_err"] = perr
    assert perr < 2e-2, f"flash prefill disagrees: {perr}"
    print(f"flash prefill compiled-mode agreement: max err {perr:.2e}")

    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "KERNEL_TPU.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
