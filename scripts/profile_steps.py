"""Microbenchmark of the compiled prefill/decode steps on the local chip.

Times (a) the full decode multi-step dispatch, (b) a single decode step,
(c) the prefill step, (d) attention-ablated variants to locate the cost.
Run: python scripts/profile_steps.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import get_config
from dynamo_tpu.ops import attention as attn
from dynamo_tpu.ops.sampling import sample_tokens

CFG = get_config("llama-3.2-1b")
PAGE = 16
B = 8
MAX_LEN = 608
W = -(-MAX_LEN // PAGE)  # pages per seq
NUM_SLOTS = (B * W + 17) * PAGE
DTYPE = jnp.bfloat16


def timeit(name, fn, *args, n=5, **kw):
    fn(*args, **kw)  # compile
    jax.block_until_ready(fn(*args, **kw))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:45s} {dt*1000:9.2f} ms")
    return dt


def main():
    dev = jax.devices()[0]
    stats = dev.memory_stats() or {}
    print("device:", dev, stats.get("bytes_limit", 0) / 1e9, "GB")

    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=DTYPE)
    kv = llama.init_kv_cache(CFG, NUM_SLOTS, dtype=DTYPE)
    kv = jax.device_put(kv)

    tables = np.stack([np.arange(1 + i * W, 1 + (i + 1) * W) for i in range(B)])
    tables = jnp.asarray(tables, jnp.int32)
    tokens = jnp.ones((B,), jnp.int32)
    positions = jnp.full((B,), 500, jnp.int32)
    temp = jnp.zeros((B,), jnp.float32)
    topk = jnp.zeros((B,), jnp.int32)
    topp = jnp.ones((B,), jnp.float32)
    key = jax.random.PRNGKey(0)

    # single decode step (T=1), full forward
    @jax.jit
    def decode1(params, kv, tokens, positions, tables, key):
        s = PAGE
        smat = (tables[:, :, None] * s + jnp.arange(s, dtype=jnp.int32)).reshape(B, -1)
        wslots = (
            jnp.take_along_axis(tables, (positions // s)[:, None], axis=1)[:, 0] * s
            + positions % s
        ).astype(jnp.int32)
        hidden, kv2 = llama.forward(
            params, CFG, tokens[:, None], positions[:, None], kv, wslots, smat
        )
        lg = llama.logits(params, CFG, hidden[:, 0])
        toks = sample_tokens(lg, key, temp, topk, topp)
        return toks, kv2

    t_step = timeit("decode single step (full fwd)", decode1,
                    params, kv, tokens, positions, tables, key)

    # forward with attention replaced by identity (isolates attention+gather)
    real_paged = attn.paged_attention
    try:
        def fake_paged(q, k_cache, v_cache, slot_matrix, positions):
            return q  # no gather, no softmax

        attn.paged_attention = fake_paged
        llama.paged_attention = fake_paged

        @jax.jit
        def decode1_noattn(params, kv, tokens, positions, tables, key):
            s = PAGE
            smat = (tables[:, :, None] * s + jnp.arange(s, dtype=jnp.int32)).reshape(B, -1)
            wslots = (
                jnp.take_along_axis(tables, (positions // s)[:, None], axis=1)[:, 0] * s
                + positions % s
            ).astype(jnp.int32)
            hidden, kv2 = llama.forward(
                params, CFG, tokens[:, None], positions[:, None], kv, wslots, smat
            )
            lg = llama.logits(params, CFG, hidden[:, 0])
            toks = sample_tokens(lg, key, temp, topk, topp)
            return toks, kv2

        timeit("decode single step (attention ablated)", decode1_noattn,
               params, kv, tokens, positions, tables, key)
    finally:
        attn.paged_attention = real_paged
        llama.paged_attention = real_paged

    # pure attention op at decode shapes, one layer x num_layers
    q = jnp.ones((B, 1, CFG.num_heads, CFG.head_dim), DTYPE)
    smat = (tables[:, :, None] * PAGE + jnp.arange(PAGE, dtype=jnp.int32)).reshape(B, -1)
    kc = kv.k[0]
    vc = kv.v[0]

    @jax.jit
    def attn_only(q, kc, vc, smat, positions):
        return attn.paged_attention(q, kc, vc, smat, positions[:, None])

    t_attn = timeit("paged_attention op (1 layer, decode)", attn_only,
                    q, kc, vc, smat, positions)
    print(f"{'  x num_layers':45s} {t_attn*1000*CFG.num_layers:9.2f} ms")

    # gather only
    @jax.jit
    def gather_only(kc, vc, smat):
        return kc[smat], vc[smat]

    t_g = timeit("KV gather only (1 layer)", gather_only, kc, vc, smat)
    print(f"{'  x num_layers':45s} {t_g*1000*CFG.num_layers:9.2f} ms")

    # 16-step scan dispatch (what the engine does)
    def decode_multi(params, kv, tokens, positions, tables, key):
        s = PAGE
        smat = (tables[:, :, None] * s + jnp.arange(s, dtype=jnp.int32)).reshape(B, -1)

        def body(carry, _):
            tokens, positions, kv, key = carry
            key, sub = jax.random.split(key)
            page_idx = jnp.minimum(positions // s, W - 1)
            wslots = (
                jnp.take_along_axis(tables, page_idx[:, None], axis=1)[:, 0] * s
                + positions % s
            )
            wslots = jnp.where(positions < MAX_LEN, wslots, 0).astype(jnp.int32)
            hidden, kv = llama.forward(
                params, CFG, tokens[:, None], positions[:, None], kv, wslots, smat
            )
            lg = llama.logits(params, CFG, hidden[:, 0])
            toks = sample_tokens(lg, sub, temp, topk, topp)
            return (toks, positions + 1, kv, key), toks

        (_, _, kv, _), out = jax.lax.scan(
            body, (tokens, positions, kv, key), None, length=16
        )
        return out, kv

    jmulti = jax.jit(decode_multi)
    t16 = timeit("decode 16-step scan dispatch", jmulti,
                 params, kv, tokens, positions, tables, key, n=3)
    print(f"{'  per token':45s} {t16*1000/16:9.2f} ms")

    # prefill 512
    T = 512
    ptok = jnp.ones((1, T), jnp.int32)
    ppos = jnp.arange(T, dtype=jnp.int32)[None]
    pws = jnp.asarray(np.arange(PAGE, PAGE + T), jnp.int32)
    psmat = smat[:1]

    @jax.jit
    def prefill(params, kv, ptok, ppos, pws, psmat, key):
        hidden, kv2 = llama.forward(params, CFG, ptok, ppos, kv, pws, psmat)
        lg = llama.logits(params, CFG, hidden[:, -1])
        toks = sample_tokens(lg, key, temp[:1], topk[:1], topp[:1])
        return toks, kv2

    timeit("prefill 512 dispatch", prefill, params, kv, ptok, ppos, pws, psmat, key, n=3)

    # dispatch overhead: trivial op
    @jax.jit
    def triv(x):
        return x + 1

    x = jnp.ones((8, 128), DTYPE)
    timeit("trivial dispatch (tunnel RTT)", triv, x, n=20)

    # device->host transfer of a tiny array (the per-dispatch sync)
    y = triv(x)
    t0 = time.perf_counter()
    for _ in range(20):
        np.asarray(y)
    print(f"{'tiny device->host':45s} {(time.perf_counter()-t0)/20*1000:9.2f} ms")


if __name__ == "__main__":
    main()
