"""Time the engine loop phases under a bench-like load (greedy, fixed
ISL/OSL) by monkeypatching the phase methods with timers.
Run: python scripts/profile_engine_loop.py [CONC]
"""

import asyncio
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)
from dynamo_tpu.runtime.pipeline.context import Context

CONC = int(sys.argv[1]) if len(sys.argv) > 1 else 64
ISL, OSL = 512, 64

times = defaultdict(float)
counts = defaultdict(int)


def wrap(obj, name):
    fn = getattr(obj, name)
    if asyncio.iscoroutinefunction(fn):
        async def timed(*a, **kw):
            t0 = time.perf_counter()
            r = await fn(*a, **kw)
            times[name] += time.perf_counter() - t0
            counts[name] += 1
            return r
    else:
        def timed(*a, **kw):
            t0 = time.perf_counter()
            r = fn(*a, **kw)
            times[name] += time.perf_counter() - t0
            counts[name] += 1
            return r
    setattr(obj, name, timed)


def main():
    engine = JaxEngine(EngineConfig(
        model="llama-3.2-1b", dtype="bfloat16",
        quantization=os.environ.get("PROF_QUANT") or None,
        max_batch_size=CONC, max_model_len=ISL + OSL + 32,
        prefill_chunk=ISL, decode_steps=int(os.environ.get("PROF_STEPS", "16")),
    ))
    for name in ("_admit_new", "_maybe_dispatch_decode", "_prefill_tick",
                 "_sync_dispatch",
                 "_prefill_chunk_dispatch", "_run_decode_dispatch"):
        wrap(engine, name)

    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 100000, ISL).tolist() for _ in range(CONC)]

    async def one(p):
        pre = PreprocessedRequest(
            token_ids=p,
            stop_conditions=StopConditions(max_tokens=OSL, ignore_eos=True),
            sampling_options=SamplingOptions(greedy=True),
        )
        n = 0
        async for f in await engine.generate(Context(pre.to_dict())):
            if f.get("token_ids"):
                n += 1
        return n

    async def run():
        await asyncio.gather(*(one(rng.randint(1, 100000, ISL).tolist()) for _ in range(CONC)))  # warmup all shapes
        for k in list(times):
            times[k] = 0.0
            counts[k] = 0
        t0 = time.perf_counter()
        out = await asyncio.gather(*(one(p) for p in prompts))
        wall = time.perf_counter() - t0
        print(f"wall {wall:.2f}s  tokens {sum(out)}  -> {sum(out)/wall:.0f} tok/s")
        for k in sorted(times, key=times.get, reverse=True):
            print(f"  {k:28s} {times[k]*1000:9.1f} ms total  x{counts[k]:5d}  "
                  f"({times[k]/max(counts[k],1)*1000:7.2f} ms/call)")

    asyncio.run(run())


if __name__ == "__main__":
    main()
