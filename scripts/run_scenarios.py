"""One entrypoint for every scenario and fleet proof (docs/loadgen.md).

    python scripts/run_scenarios.py [--scenarios chat,bursty|all]
                                    [--out path.json] [--scale tiny|real]

Runs the loadgen scenario registry (dynamo_tpu/loadgen/scenarios.py) —
including the prefix_fleet and control_chaos fleet proofs when selected
— validates every emitted section against the scenarios contract
(SLO-gated goodput + TTFT/ITL percentiles + throughput present, no
errors), prints the JSON, and exits non-zero on a malformed or failed
scenario. CI's ``scenario-smoke`` job runs a 3-scenario subset.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Compile-census flat budget (jit compiles per scenario). The FIRST
# scenario in a cold process pays the whole tiny-engine variant set
# (~80 on CPU today); warm scenarios reuse the process jit cache and
# sit an order of magnitude lower (~11). A change that mints a new
# variant family per shape — e.g. a KV-quant flag leaking into
# trace-level dynamism instead of staying a static aux — multiplies the
# cold set and trips this long before it reads as a latency regression.
COMPILE_EVENTS_BUDGET = int(
    os.environ.get("LOADGEN_COMPILE_BUDGET", "150")
)


def check_section(name: str, out: dict) -> list[str]:
    """Contract violations for one scenario section ([] = well-formed)."""
    bad = []
    if "error" in out:
        return [f"{name}: scenario errored: {out['error']}"]
    if out.get("kind") == "fleet_adapter":
        if not isinstance(out.get("fleet"), dict) or not out["fleet"]:
            bad.append(f"{name}: fleet adapter carried no payload")
        return bad
    gp = out.get("goodput") or {}
    if gp.get("goodput_toks_per_sec") is None:
        bad.append(f"{name}: missing goodput_toks_per_sec")
    elif gp["goodput_toks_per_sec"] <= 0:
        bad.append(f"{name}: zero goodput ({gp})")
    if gp.get("attained_frac") is None:
        bad.append(f"{name}: missing SLO attained_frac")
    for metric in ("ttft", "itl"):
        for q in ("p50_s", "p99_s"):
            if (out.get(metric) or {}).get(q) is None:
                bad.append(f"{name}: missing {metric}.{q}")
    if out.get("throughput_toks_per_sec") is None:
        bad.append(f"{name}: missing throughput")
    reqs = out.get("requests") or {}
    if reqs.get("errors"):
        bad.append(f"{name}: {reqs['errors']} request errors")
    if (out.get("trace") or {}).get("sha256") is None:
        bad.append(f"{name}: missing trace identity")
    comp = out.get("compile") or {}
    if comp.get("events") is None:
        bad.append(f"{name}: missing compile census")
    elif comp["events"] > COMPILE_EVENTS_BUDGET:
        bad.append(
            f"{name}: compile census blew the flat budget — "
            f"{comp['events']} jit compiles in one scenario "
            f"(budget {COMPILE_EVENTS_BUDGET}); a new variant family "
            "is being minted per shape"
        )
    return bad


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenarios", default=None,
                    help="csv of scenario names, 'default' or 'all' "
                         "(default: LOADGEN_SCENARIOS env or 'default')")
    ap.add_argument("--scale", default=None, choices=["tiny", "real"],
                    help="default: LOADGEN_SCALE env or tiny")
    ap.add_argument("--out", default=None, help="also write JSON here")
    ap.add_argument("--list", action="store_true",
                    help="list registered scenarios and exit")
    args = ap.parse_args()

    if args.scenarios is not None:
        os.environ["LOADGEN_SCENARIOS"] = args.scenarios
    if args.scale is not None:
        os.environ["LOADGEN_SCALE"] = args.scale

    from dynamo_tpu.loadgen import bench as loadgen_bench
    from dynamo_tpu.loadgen.scenarios import SCENARIOS

    if args.list:
        for name, spec in SCENARIOS.items():
            kind = " [fleet]" if spec.fleet else ""
            print(f"{name}{kind}: {spec.description}")
        return 0

    section = loadgen_bench.run_suite()
    print(json.dumps(section, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(section, f, indent=2)
            f.write("\n")

    problems = []
    failing = []
    for name, out in section["results"].items():
        bad = check_section(name, out)
        problems.extend(bad)
        if bad:
            failing.append(name)
    if problems:
        for p in problems:
            print(f"MALFORMED: {p}", file=sys.stderr)
        # forensics: dump every registered flight-recorder ring (the
        # scenario engines register theirs at init and the registry
        # outlives them) NEXT TO the replayable traces, so the CI
        # scenario-smoke upload carries the step digests + trace slice
        # of the failing run, not just its arrival schedule
        from dynamo_tpu.engine import flight_recorder

        art_dir = os.environ.get("LOADGEN_TRACE_DIR") or None
        for path in flight_recorder.dump_all(
            "scenario:" + ",".join(sorted(failing)), directory=art_dir
        ):
            print(f"flight-recorder artifact: {path}", file=sys.stderr)
        return 1
    print(
        f"{len(section['results'])} scenario(s) well-formed "
        f"(scale={section['scale']['name']})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
