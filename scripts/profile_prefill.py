"""Prefill ablations: why does a 512-token prefill cost ~57 ms?

Run: python scripts/profile_prefill.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import get_config
from dynamo_tpu.ops import attention as attn
from dynamo_tpu.ops.sampling import sample_tokens

CFG = get_config("llama-3.2-1b")
PAGE = 16
T = 512
W = 38
NUM_SLOTS = (8 * W + 17) * PAGE
DTYPE = jnp.bfloat16


def timeit(name, fn, *args, n=10, **kw):
    jax.block_until_ready(fn(*args, **kw))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:55s} {dt*1000:9.2f} ms")
    return dt


def main():
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=DTYPE)
    kv = jax.device_put(llama.init_kv_cache(CFG, NUM_SLOTS, dtype=DTYPE))
    ptok = jnp.ones((1, T), jnp.int32)
    ppos = jnp.arange(T, dtype=jnp.int32)[None]
    pws = jnp.asarray(np.arange(PAGE, PAGE + T), jnp.int32)
    smat_full = jnp.asarray(
        (np.arange(1, 1 + W)[:, None] * PAGE + np.arange(PAGE)).reshape(1, -1),
        jnp.int32,
    )
    smat_tight = smat_full[:, : T]  # exactly the chunk's slots
    key = jax.random.PRNGKey(0)
    temp = jnp.zeros((1,), jnp.float32)
    topk = jnp.zeros((1,), jnp.int32)
    topp = jnp.ones((1,), jnp.float32)

    def run(smat, attn_mode="gather", sample=True, batch=1):
        tok = jnp.tile(ptok, (batch, 1))
        pos = jnp.tile(ppos, (batch, 1))
        ws = jnp.tile(pws, (batch,))  # aliasing writes; timing only
        sm = jnp.tile(smat, (batch, 1))

        def fn(params, kv, tok, pos, ws, sm, key):
            real = attn.paged_attention
            if attn_mode == "causal":
                def causal(q, kc, vc, smat_, positions):
                    b, t, h, hd = q.shape
                    kh = kc.shape[1]
                    # direct chunk attention: K/V just written are the chunk
                    k = kc[smat_[:, : t]]
                    v = vc[smat_[:, : t]]
                    g = h // kh
                    qg = q.reshape(b, t, kh, g, hd)
                    lg = jnp.einsum(
                        "btkgd,bskd->bkgts", qg, k,
                        preferred_element_type=jnp.float32,
                    ) * (hd ** -0.5)
                    mask = (
                        jnp.arange(t)[None, :] <= jnp.arange(t)[:, None]
                    )[None, None, None]
                    lg = jnp.where(mask, lg, -1e30)
                    p = jax.nn.softmax(lg, axis=-1)
                    o = jnp.einsum("bkgts,bskd->btkgd", p.astype(v.dtype), v)
                    return o.reshape(b, t, h, hd)

                attn.paged_attention = causal
                llama.paged_attention = causal
            elif attn_mode == "none":
                attn.paged_attention = lambda q, *a: q
                llama.paged_attention = attn.paged_attention
            try:
                hidden, kv2 = llama.forward(params, CFG, tok, pos, kv, ws, sm)
            finally:
                attn.paged_attention = real
                llama.paged_attention = real
            lg = llama.logits(params, CFG, hidden[:, -1])
            if sample:
                toks = sample_tokens(
                    lg, key,
                    jnp.tile(temp, (batch,)), jnp.tile(topk, (batch,)),
                    jnp.tile(topp, (batch,)),
                )
            else:
                toks = jnp.argmax(lg, -1)
            return toks, kv2

        return jax.jit(fn), (params, kv, tok, pos, ws, sm, key)

    for name, (fn, args) in [
        ("full prefill 512 (gather, C=608)", run(smat_full)),
        ("gather C=512 (tight smat)", run(smat_tight)),
        ("direct causal chunk attention", run(smat_tight, attn_mode="causal")),
        ("no attention", run(smat_tight, attn_mode="none")),
        ("batch=4 prefill, causal", run(smat_tight, attn_mode="causal", batch=4)),
        ("batch=4 prefill, gather C=608", run(smat_full, batch=4)),
    ]:
        timeit(name, fn, *args)


if __name__ == "__main__":
    main()
