"""Prefill ablation at serving shapes: where does the [n, 512] chunked
prefill step spend its time? Methodology: n chained dispatches (scan over
independent chunk batches) ended by a value fetch — stable through the
tunnel. Run: python scripts/profile_prefill.py [n_rows]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import dynamo_tpu.ops.attention as A
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import get_config
from dynamo_tpu.ops.sampling import sample_tokens

N = int(sys.argv[1]) if len(sys.argv) > 1 else 64
T = 512
CFG = get_config("llama-3.2-1b")
PAGE = 64
W = -(-(T + 128) // PAGE)
C = W * PAGE
NUM_SLOTS = (N * W + 17) * PAGE
DTYPE = jnp.bfloat16
REPS = 4
SCAN = 4  # chunk batches per dispatch


def run(name, *, attn=True, logits=True, write=True):
    smat_np = np.stack(
        [np.arange(1 + i * W, 1 + (i + 1) * W) for i in range(N)]
    )
    smat = (
        jnp.asarray(smat_np, jnp.int32)[:, :, None] * PAGE
        + jnp.arange(PAGE, dtype=jnp.int32)
    ).reshape(N, -1)
    wslots = (smat[:, :T]).reshape(-1)
    temp = jnp.zeros((N,), jnp.float32)
    topk = jnp.zeros((N,), jnp.int32)
    topp = jnp.ones((N,), jnp.float32)
    last = jnp.full((N,), T - 1, jnp.int32)

    mode = os.environ.get("PROF_MODE", "oracle")  # oracle|write|flash
    ppc = T // PAGE
    wtables = jnp.asarray(smat_np[:, :ppc], jnp.int32).reshape(-1)
    btables = jnp.asarray(smat_np, jnp.int32)
    tlen = jnp.full((N,), T, jnp.int32)
    pos0 = jnp.zeros((N,), jnp.int32)

    def step(params, kv, tokens, positions, key):
        def body(carry, _):
            kv, key = carry
            key, sub = jax.random.split(key)
            if mode == "flash":
                spec = llama.AttnSpec.gather(
                    smat, write_tables=wtables, page_size=PAGE,
                    block_tables=btables, q_pos0=pos0, lengths=tlen,
                )
            elif mode == "write":
                spec = llama.AttnSpec.gather(
                    smat, write_tables=wtables, page_size=PAGE
                )
            else:
                spec = smat
            hidden, kv = llama.forward(
                params, CFG, tokens, positions, kv, wslots, spec
            )
            if logits:
                lh = jnp.take_along_axis(
                    hidden, last[:, None, None].astype(jnp.int32), axis=1
                )[:, 0]
                lg = llama.logits(params, CFG, lh)
                toks = sample_tokens(lg, sub, temp, topk, topp, all_greedy=True)
            else:
                toks = tokens[:, 0]
            return (kv, key), toks

        (kv, _), out = jax.lax.scan(body, (kv, key), None, length=SCAN)
        return out, kv

    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=DTYPE)
    kv = jax.device_put(llama.init_kv_cache(CFG, NUM_SLOTS, dtype=DTYPE))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(1, CFG.vocab_size, (N, T)), jnp.int32
    )
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (N, 1))
    key = jax.random.PRNGKey(0)

    real_attn, real_write = A.paged_attention, A.write_kv_slots
    la, lw = llama.paged_attention, llama.write_kv_slots
    try:
        if not attn:
            fake = lambda q, kc, vc, sm, pos: q
            A.paged_attention = fake
            llama.paged_attention = fake
        if not write:
            noww = lambda kc, vc, s, nk, nv: (kc, vc)
            A.write_kv_slots = noww
            llama.write_kv_slots = noww
        f = jax.jit(step, donate_argnums=(1,))
        out, kv2 = f(params, kv, tokens, positions, key)
        _ = np.asarray(out[-1, :1])
        t0 = time.perf_counter()
        for _ in range(REPS):
            out, kv2 = f(params, kv2, tokens, positions, key)
        _ = np.asarray(out[-1, :1])
        dt = (time.perf_counter() - t0) / REPS / SCAN
        toks = N * T
        flops = 2 * 1.24e9 * toks
        print(
            f"{name:42s} {dt * 1e3:8.2f} ms/chunk-batch "
            f"({toks / dt / 1e3:7.1f}k tok/s, {flops / dt / 1e12:5.1f} TF/s)",
            flush=True,
        )
        return dt
    finally:
        A.paged_attention, A.write_kv_slots = real_attn, real_write
        llama.paged_attention, llama.write_kv_slots = la, lw


if __name__ == "__main__":
    print(f"prefill ablation: n={N} T={T} C={C} page={PAGE}")
    run("full")
    run("no logits/sampling", logits=False)
    run("no attention", attn=False, logits=False)
    run("no attention, no write", attn=False, write=False, logits=False)
