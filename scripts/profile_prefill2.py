"""Prototype: page-granular gather/write vs row-granular for prefill.
Hypothesis: XLA row gather/scatter serializes per row (~0.45us/row), so
gathering [B, W] whole pages (64x fewer, 64KB each) and writing whole
pages should cut prefill attention from ~590ms to ~tens of ms.
Run: python scripts/profile_prefill2.py [n_rows]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 64
T = 512
PAGE = 64
W = -(-(T + 128) // PAGE)
C = W * PAGE
KW = 8 * 64  # K*Hd for llama-1b
NUM_SLOTS = (N * W + 17) * PAGE
NUM_PAGES = NUM_SLOTS // PAGE
DTYPE = jnp.bfloat16
L = 16  # simulate 16 layers' worth of traffic
REPS = 4


def bench(name, fn, *args):
    out = fn(*args)
    _ = np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
    _ = np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    dt = (time.perf_counter() - t0) / REPS
    print(f"{name:44s} {dt * 1e3 / L:8.3f} ms/layer ({dt * 1e3:7.1f} ms total)",
          flush=True)
    return dt


def main():
    rng = np.random.RandomState(0)
    kc = jnp.asarray(rng.randn(NUM_SLOTS, KW), DTYPE)
    tables_np = np.stack(
        [np.arange(1 + i * W, 1 + (i + 1) * W) for i in range(N)]
    ).astype(np.int32)
    tables = jnp.asarray(tables_np)
    smat = (
        tables[:, :, None] * PAGE + jnp.arange(PAGE, dtype=jnp.int32)
    ).reshape(N, -1)
    new_rows = jnp.asarray(rng.randn(N * T, KW), DTYPE)
    wslots = smat[:, :T].reshape(-1)

    # row gather: [B*C] rows
    @jax.jit
    def row_gather(kc):
        acc = jnp.zeros((), jnp.float32)
        for _ in range(L):
            k = kc[smat]                    # [N, C, KW]
            acc = acc + jnp.sum(k[:, 0, 0].astype(jnp.float32))
        return acc

    # page gather: [B*W] pages via reshape view
    @jax.jit
    def page_gather(kc):
        acc = jnp.zeros((), jnp.float32)
        kp = kc.reshape(NUM_PAGES, PAGE, KW)
        for _ in range(L):
            k = kp[tables]                  # [N, W, PAGE, KW]
            acc = acc + jnp.sum(k[:, 0, 0, 0].astype(jnp.float32))
        return acc

    # row scatter write
    @jax.jit
    def row_write(kc, rows):
        for _ in range(L):
            kc = kc.at[wslots].set(rows)
        return kc

    # page scatter write (chunk page-aligned: T covers whole pages)
    n_full = T // PAGE
    write_pages = tables[:, :n_full].reshape(-1)  # [N*n_full]

    @jax.jit
    def page_write(kc, rows):
        pages = rows.reshape(N, n_full, PAGE, KW).reshape(-1, PAGE, KW)
        for _ in range(L):
            kp = kc.reshape(NUM_PAGES, PAGE, KW)
            kp = kp.at[write_pages].set(pages)
            kc = kp.reshape(NUM_SLOTS, KW)
        return kc

    print(f"n={N} T={T} W={W} pages_gathered={N * W} rows_gathered={N * C}")
    bench("row gather  (16x [N*C] rows)", row_gather, kc)
    bench("page gather (16x [N*W] pages)", page_gather, kc)
    bench("row write   (16x [N*T] rows)", row_write, kc, new_rows)
    bench("page write  (16x [N*T/page] pages)", page_write, kc, new_rows)


if __name__ == "__main__":
    main()
