"""Bench-history trend + regression gate over the BENCH_r*.json trajectory.

The repo carries one machine-readable bench artifact per external run
(``BENCH_r01.json`` …) and ``bench.py`` writes the same shape fresh via
``BENCH_OUT`` — but until now nothing READ the trajectory, so a win lost
quietly (the 0.68x warm-TTFT class) surfaced at re-anchor time instead
of at PR time. This tool closes that loop:

- **normalize** every run (old harness wrappers ``{n, cmd, rc, parsed}``
  and the sectioned BENCH_OUT shape both) into a flat metric set with a
  comparability *context* per metric — a tiny-model headline is never
  compared against a llama-scale one, an ISL=64 probe never against
  ISL=160 (contexts must match exactly);
- **print a trend table** (runs × metrics, with each run's
  ``extra.rev`` commit join when stamped);
- with ``--fresh BENCH_OUT.json``, **gate**: each fresh metric is
  compared against the most recent comparable historical value and the
  tool exits non-zero when one regressed beyond its per-metric
  tolerance (relative + a small absolute floor for near-zero
  fractions). History-only mode never fails — the trajectory is a
  record, not a promise; only a FRESH run is judged.

Usage:
    python scripts/bench_history.py                      # trend table
    python scripts/bench_history.py --fresh out.json     # gate a run
    python scripts/bench_history.py --history-glob 'dir/BENCH_r*.json' \
        --fresh out.json --json                          # CI form
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass
class Metric:
    value: float
    higher_better: bool
    context: str          # must match exactly for two runs to compare
    rtol: float = 0.10    # relative tolerance before a delta is a regression
    atol: float = 0.0     # absolute floor (near-zero fractions are noisy)


def _ctx(*parts) -> str:
    return "|".join(str(p) for p in parts)


def _scenario_key(section: dict) -> str:
    """Stable serialization of a section's own `scenario` descriptor —
    the scale key for sections that carry one (prefix_fleet, control)."""
    sc = section.get("scenario")
    return (
        json.dumps(sc, sort_keys=True) if isinstance(sc, dict) else "-"
    )


def _num(x) -> Optional[float]:
    return float(x) if isinstance(x, (int, float)) and not isinstance(
        x, bool
    ) else None


def normalize(doc: dict) -> dict:
    """One run (either wire shape) -> {"rev", "ts", "ok", "metrics"}."""
    if "rc" in doc and "cmd" in doc:
        # old external-harness wrapper: parsed holds the headline only
        if doc.get("rc") != 0 or not isinstance(doc.get("parsed"), dict):
            return {"rev": None, "ts": None, "ok": False, "metrics": {}}
        doc = {"headline": doc["parsed"]}
    metrics: dict[str, Metric] = {}
    rev = ts = None

    def note_prov(section: dict) -> None:
        nonlocal rev, ts
        extra = section.get("extra") or {}
        rev = rev or extra.get("rev")
        ts = ts or extra.get("ts")

    headline = doc.get("headline")
    if isinstance(headline, dict):
        note_prov(headline)
        extra = headline.get("extra") or {}
        v = _num(headline.get("value"))
        if v is not None:
            # context = the full metric string (model + ISL/OSL/conc):
            # the r06 lesson — a tiny headline must never be compared
            # against the llama-scale trajectory
            hctx = _ctx("headline", headline.get("metric"))
            metrics["headline.toks_per_sec_chip"] = Metric(
                v, True, hctx, rtol=0.15
            )
            sp = _num(extra.get("prefix_hit_ttft_speedup"))
            if sp is not None:
                metrics["prefix.hit_ttft_speedup"] = Metric(
                    sp, True, hctx, rtol=0.15, atol=0.05
                )
    spec = doc.get("spec")
    if isinstance(spec, dict) and _num(spec.get("speedup")) is not None:
        note_prov(spec)
        metrics["spec.speedup"] = Metric(
            _num(spec["speedup"]), True,
            _ctx("spec", spec.get("k_max"), spec.get("osl"),
                 spec.get("concurrency")),
            rtol=0.25,
        )
    mixed = doc.get("mixed")
    if isinstance(mixed, dict) and _num(
        mixed.get("itl_p99_speedup")
    ) is not None:
        note_prov(mixed)
        metrics["mixed.itl_p99_speedup"] = Metric(
            _num(mixed["itl_p99_speedup"]), True,
            _ctx("mixed", mixed.get("step_tokens"),
                 mixed.get("held_streams"), mixed.get("wave_prompts")),
            rtol=0.30,
        )
    ms = doc.get("mixed_spec")
    if isinstance(ms, dict) and _num(ms.get("itl_p99_ratio")) is not None:
        note_prov(ms)
        # ratio of mixed+spec p99 over mixed-only p99: LOWER is better
        metrics["mixed_spec.itl_p99_ratio"] = Metric(
            _num(ms["itl_p99_ratio"]), False,
            _ctx("mixed_spec", ms.get("step_tokens"),
                 ms.get("held_streams")),
            rtol=0.30, atol=0.1,
        )
    pab = doc.get("pipeline_ab")
    if isinstance(pab, dict):
        note_prov(pab)
        sf = _num((pab.get("pipelined") or {}).get("sync_frac"))
        if sf is not None:
            # true-stall fraction of the step wall: lower is better,
            # and near zero — the absolute floor carries the judgment.
            # The A/B legs run on the HEADLINE engine, so the headline
            # metric string (model + ISL/OSL/conc) is the scale key: a
            # tiny-CI smoke must not gate a real-model trajectory
            metrics["pipeline.sync_frac"] = Metric(
                sf, False,
                _ctx(
                    "pipeline_ab",
                    (headline or {}).get("metric")
                    if isinstance(headline, dict) else None,
                ),
                rtol=0.5, atol=0.02,
            )
    goodput = doc.get("goodput")
    if isinstance(goodput, dict):
        note_prov(goodput)
        slo = goodput.get("slo") or {}
        v = _num(slo.get("goodput_toks_per_sec_chip"))
        hctx = _ctx(
            "goodput",
            (headline or {}).get("metric") if isinstance(headline, dict)
            else None,
        )
        if v is not None:
            metrics["goodput.toks_per_sec_chip"] = Metric(
                v, True, hctx, rtol=0.25
            )
        af = _num(slo.get("attained_frac"))
        if af is not None:
            metrics["goodput.attained_frac"] = Metric(
                af, True, hctx, rtol=0.10, atol=0.05
            )
    pf = doc.get("prefix_fleet")
    if isinstance(pf, dict):
        note_prov(pf)
        # the section's own scenario descriptor (tenants/page/prefix
        # pages/...) IS its scale: runs only compare when the probe
        # shape matches exactly
        pctx = _ctx("prefix_fleet", _scenario_key(pf))
        v = _num(pf.get("warm_vs_cold_ttft"))
        if v is not None:
            metrics["prefix_fleet.warm_vs_cold_ttft"] = Metric(
                v, True, pctx, rtol=0.20, atol=0.05
            )
        v = _num(pf.get("route_to_holder_frac"))
        if v is not None:
            metrics["prefix_fleet.route_to_holder_frac"] = Metric(
                v, True, pctx, rtol=0.20, atol=0.05
            )
    control = doc.get("control")
    if isinstance(control, dict):
        note_prov(control)
        # scale = the chaos scenario's own shape (workers/budget/rps/
        # fault point) — a tiny smoke never gates a bigger replay
        cctx = _ctx("control", _scenario_key(control))
        ttr = _num(control.get("time_to_recover_s"))
        if ttr is not None:
            # recovery time at tiny scale is sampler-quantized: generous
            # relative + absolute slack
            metrics["control.time_to_recover_s"] = Metric(
                ttr, False, cctx, rtol=1.0, atol=3.0
            )
        v = _num((control.get("goodput") or {}).get("retained"))
        if v is not None:
            metrics["control.goodput_retained"] = Metric(
                v, True, cctx, rtol=0.25, atol=0.1
            )
    kvcap = doc.get("kv_capacity")
    if isinstance(kvcap, dict):
        note_prov(kvcap)
        # scale = the census's own descriptor (budget/ISL/OSL/head_dim/
        # group): a different budget or shape is a different experiment
        kctx = _ctx("kv_capacity", _scenario_key(kvcap))
        cap = kvcap.get("capacity") or {}
        v = _num(cap.get("capacity_ratio_int4_vs_bf16"))
        if v is not None:
            # pure pool-byte arithmetic + floor division — deterministic
            # at fixed shape, so the tolerance is tight
            metrics["kv_capacity.int4_vs_bf16_streams"] = Metric(
                v, True, kctx, rtol=0.02
            )
        v = _num(cap.get("data_ratio_int4_vs_bf16"))
        if v is not None:
            # exact by construction (4.0): any drift means the packed
            # pool layout changed under the allocator
            metrics["kv_capacity.int4_data_ratio"] = Metric(
                v, True, kctx, rtol=0.001
            )
        for tier in ("int8", "int4"):
            v = _num(
                ((kvcap.get("quality") or {}).get("tiers") or {})
                .get(tier, {}).get("greedy_token_match")
            )
            if v is not None:
                metrics[f"kv_capacity.{tier}_token_match"] = Metric(
                    v, True, kctx, rtol=0.03, atol=0.02
                )
        v = _num((kvcap.get("throughput") or {}).get("int4_vs_int8"))
        if v is not None:
            # CPU wall-clock at tiny scale: wide tolerance, trend only
            metrics["kv_capacity.int4_vs_int8_toks"] = Metric(
                v, True, kctx, rtol=0.50, atol=0.2
            )
    tov = doc.get("tp_overlap")
    if isinstance(tov, dict):
        note_prov(tov)
        # the leg's backend/KV-tier descriptor IS the comparability key:
        # a pallas/quantized leg must never gate against the gather-
        # backend baseline trajectory (different kernels, different byte
        # floors), and a shape change is a different experiment
        base_ctx = _ctx(
            "tp_overlap", tov.get("model"), tov.get("tp"),
            tov.get("rows"), tov.get("hidden_size"), "gather", "bf16",
        )
        v = _num(tov.get("exposed_ratio"))
        if v is not None:
            # exactly 0.5 by construction — drift means the ring
            # executor's byte schedule changed
            metrics["tp_overlap.exposed_ratio"] = Metric(
                v, False, base_ctx, rtol=0.001
            )
        v = _num(tov.get("layer_step_overlap_speedup"))
        if v is not None:
            # CPU virtual-device wall: scheduling-shape trend only
            metrics["tp_overlap.layer_step_speedup"] = Metric(
                v, True, base_ctx, rtol=0.5, atol=0.2
            )
        for tier, leg in (tov.get("pallas_legs") or {}).items():
            if not isinstance(leg, dict):
                continue
            lctx = _ctx(
                "tp_overlap", tov.get("model"), tov.get("tp"),
                tov.get("rows"), tov.get("hidden_size"),
                leg.get("backend"), tier,
                "packed" if leg.get("kv_packed") else "dense",
            )
            v = _num(leg.get("exposed_ratio"))
            if v is not None:
                metrics[f"tp_overlap.pallas_{tier}.exposed_ratio"] = Metric(
                    v, False, lctx, rtol=0.001
                )
            ov_w = _num(leg.get("layer_step_wall_s"))
            fb_w = _num(leg.get("fallback_layer_step_wall_s"))
            if ov_w and fb_w:
                metrics[f"tp_overlap.pallas_{tier}.wall_vs_fallback"] = (
                    Metric(ov_w / fb_w, False, lctx, rtol=0.5, atol=0.25)
                )
    scenarios = doc.get("scenarios")
    if isinstance(scenarios, dict):
        note_prov(scenarios)
        scale = scenarios.get("scale") or {}
        sctx = _ctx(
            "scenarios", scale.get("name"), scale.get("n"),
            scale.get("rate_rps"), scale.get("seed"),
        )
        for name, out in (scenarios.get("results") or {}).items():
            if not isinstance(out, dict) or "error" in out:
                continue
            v = _num((out.get("goodput") or {}).get("goodput_toks_per_sec"))
            if v is not None:
                metrics[f"scenario.{name}.goodput"] = Metric(
                    v, True, sctx, rtol=0.40
                )
    return {"rev": rev, "ts": ts, "ok": True, "metrics": metrics}


def load_history(pattern: str) -> list[tuple[str, dict]]:
    """[(run_name, normalized)] sorted by the numeric run suffix."""
    def run_key(path: str):
        m = re.search(r"r(\d+)", os.path.basename(path))
        return (int(m.group(1)) if m else 0, path)

    out = []
    for path in sorted(glob.glob(pattern), key=run_key):
        name = re.sub(
            r"^BENCH_|\.json$", "", os.path.basename(path)
        )
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"bench_history: skipping {path}: {exc}",
                  file=sys.stderr)
            continue
        out.append((name, normalize(doc)))
    return out


def baseline_for(
    key: str, metric: Metric, history: list[tuple[str, dict]]
) -> Optional[tuple[str, Metric]]:
    """Most recent historical value of `key` with a MATCHING context."""
    for name, run in reversed(history):
        prior = run["metrics"].get(key)
        if prior is not None and prior.context == metric.context:
            return name, prior
    return None


def judge(
    fresh: dict, history: list[tuple[str, dict]], scale: float = 1.0
) -> list[dict]:
    """Per fresh metric: {key, verdict, ...}. verdict in
    ok|regressed|improved|new (new = no comparable baseline)."""
    rows = []
    for key, m in sorted(fresh["metrics"].items()):
        base = baseline_for(key, m, history)
        if base is None:
            rows.append({"key": key, "verdict": "new", "value": m.value})
            continue
        bname, bm = base
        rtol, atol = m.rtol * scale, m.atol * scale
        if m.higher_better:
            floor = bm.value * (1 - rtol) - atol
            regressed = m.value < floor
            improved = m.value > bm.value * (1 + rtol) + atol
        else:
            ceil = bm.value * (1 + rtol) + atol
            regressed = m.value > ceil
            improved = m.value < bm.value * (1 - rtol) - atol
        rows.append({
            "key": key,
            "verdict": (
                "regressed" if regressed
                else "improved" if improved else "ok"
            ),
            "value": m.value,
            "baseline": bm.value,
            "baseline_run": bname,
            "delta_frac": (
                round(m.value / bm.value - 1, 4) if bm.value else None
            ),
            "direction": "higher" if m.higher_better else "lower",
            "rtol": rtol,
        })
    return rows


def print_trend(history: list[tuple[str, dict]], fresh=None) -> None:
    runs = list(history) + ([("fresh", fresh)] if fresh else [])
    keys = sorted({k for _, r in runs for k in r["metrics"]})
    if not keys:
        print("bench_history: no comparable metrics in any run")
        return
    name_w = max(len(k) for k in keys) + 2
    header = "metric".ljust(name_w) + "".join(
        n.rjust(12) for n, _ in runs
    )
    print(header)
    revs = "rev".ljust(name_w) + "".join(
        (str(r.get("rev") or "-")[:8]).rjust(12) for _, r in runs
    )
    print(revs)
    print("-" * len(header))
    for key in keys:
        cells = []
        for _, run in runs:
            m = run["metrics"].get(key)
            cells.append(
                f"{m.value:.4g}".rjust(12) if m is not None
                else "-".rjust(12)
            )
        print(key.ljust(name_w) + "".join(cells))


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--history-glob",
        default=os.path.join(REPO_ROOT, "BENCH_r*.json"),
        help="glob of historical run artifacts "
             "(default: <repo>/BENCH_r*.json)",
    )
    ap.add_argument(
        "--fresh",
        help="a fresh BENCH_OUT file to gate against the trajectory; "
             "omitted = trend-only mode (always exits 0)",
    )
    ap.add_argument(
        "--tolerance-scale", type=float, default=1.0,
        help="multiply every per-metric tolerance (loosen on noisy CI "
             "runners)",
    )
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict rows as JSON on stdout")
    args = ap.parse_args(argv)

    history = load_history(args.history_glob)
    failed_runs = [n for n, r in history if not r["ok"]]

    fresh = None
    if args.fresh:
        with open(args.fresh) as f:
            fresh = normalize(json.load(f))

    if not args.json:
        print_trend(history, fresh)
        if failed_runs:
            print(f"(runs with no parseable result: "
                  f"{', '.join(failed_runs)})")

    if fresh is None:
        return 0

    rows = judge(fresh, history, scale=args.tolerance_scale)
    if args.json:
        print(json.dumps({"verdicts": rows}, indent=2))
    regressions = [r for r in rows if r["verdict"] == "regressed"]
    if not args.json:
        print()
        for r in rows:
            if r["verdict"] == "new":
                print(f"  NEW        {r['key']} = {r['value']:.4g} "
                      f"(no comparable baseline)")
            else:
                delta = (
                    f"{r['delta_frac']:+.1%}"
                    if r["delta_frac"] is not None else "n/a vs 0"
                )
                print(
                    f"  {r['verdict'].upper():<10} {r['key']} = "
                    f"{r['value']:.4g} vs {r['baseline']:.4g} "
                    f"[{r['baseline_run']}] "
                    f"({delta}, {r['direction']} is "
                    f"better, tol ±{r['rtol']:.0%})"
                )
    if regressions:
        print(
            f"bench_history: {len(regressions)} regression(s) beyond "
            f"tolerance", file=sys.stderr,
        )
        return 1
    n_cmp = sum(1 for r in rows if r["verdict"] != "new")
    print(
        f"bench_history ok: {n_cmp} metric(s) within tolerance, "
        f"{len(rows) - n_cmp} new", file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
