"""Multi-tenant shared-prefix FLEET scenario: prove the KV cache plane
pays at the fleet level, not just inside one engine (docs/kv_cache.md).

Million-user traffic is dominated by shared prefixes (system prompts,
few-shot templates, multi-turn). This scenario replays that shape
against the whole KV plane with REAL components in one process:

    HubServer <- 2 x { JaxEngine + KvEventPublisher + KvMetricsPublisher
                       + KvExportHandler + PrefixPuller }  (workers)
        ^
    KvPushRouter (radix indexer fed live engine events, tier-weighted
    selector, saturation-aware cross-worker pull decision)

Phases:

1. **cold** — T tenants, each with a distinct shared prefix (several
   full pages) + a per-request suffix, routed through the KV router;
   nothing is cached anywhere. Tenant TTFTs here are the recompute bar.
2. **warm** — fresh suffixes on the same tenant prefixes. The router's
   indexer has ingested the workers' stored-block events, so requests
   route to the worker already holding their prefix and ride its cache.
   Scored: warm-vs-cold TTFT (target >= 1.3x on TPU), the fraction
   routed back to the holder, and the fraction whose ledger shows real
   block reuse.
3. **pull** — the holder of one tenant's prefix is SATURATED (held
   decode streams fill its slots). New requests for that tenant would
   previously recompute the prefix on the idle worker; now the router
   routes them there with ``kv_pull_from`` metadata and the worker
   PULLS the prefix from the holder (export_prefix -> ingest_prefix)
   instead. Scored: pulls landed + tokens moved.

The $-per-million-tokens line converts each phase's wall into dollars
at BENCH_CHIP_HOUR_USD (default 1.20 $/chip-hour, v5e-class on-demand):
the warm phase serving the same token volume in less wall IS the cache
economics, in the unit the ROADMAP asks for.

Emits one JSON dict (the ``prefix_fleet`` BENCH_OUT section); run
directly it prints the JSON and exits non-zero when the plane failed
(no routing reuse, or no pull landed). Also registered in the loadgen
scenario registry as the ``prefix_fleet`` adapter (docs/loadgen.md),
so ``scripts/run_scenarios.py --scenarios all`` runs this proof too.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402

from dynamo_tpu.engine.kv_ledger import quiesce_census  # noqa: E402
from dynamo_tpu.runtime.component import EndpointId  # noqa: E402
from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: E402
from dynamo_tpu.runtime.hub.server import HubServer  # noqa: E402
from dynamo_tpu.runtime.pipeline.context import Context  # noqa: E402
from dynamo_tpu.utils import counters  # noqa: E402

NS, COMP, EP = "fleet", "backend", "generate"


def _defaults() -> dict:
    """Tiny-scale defaults (CPU CI finishes in well under a minute)."""
    return dict(
        tenants=4,            # distinct shared prefixes
        page=16,              # KV page/block size (gather backend)
        prefix_pages=6,       # full pages per shared prefix
        suffix=8,             # fresh per-request suffix tokens
        osl=8,                # generated tokens per request
        cold_per_tenant=1,
        warm_per_tenant=3,
        pull_requests=2,      # pull-phase requests on the saturated tenant
        max_batch=2,          # worker decode slots (saturation = 2 held)
        num_pages=256,
        hold_osl=64,          # held-stream length during the pull phase
        pull_threshold_pages=2,
        poll_interval=0.25,   # aggregator scrape cadence
        chip_hour_usd=float(os.environ.get("BENCH_CHIP_HOUR_USD", "1.20")),
        # KV pool tier for the fleet workers (None = engine-dtype KV).
        # BENCH_PREFIX_FLEET_KV=int8|int4 runs the SAME routing/pull
        # economics on quantized pools — the cross-worker pulls then
        # move packed bytes (quantize-once: export/ingest carries the
        # pool representation, never a requantization hop)
        kv_quant=(os.environ.get("BENCH_PREFIX_FLEET_KV") or None),
    )


def _phase_dollars(tokens: int, wall_s: float, usd_hour: float) -> dict:
    return {
        "tokens": tokens,
        "wall_s": round(wall_s, 4),
        "toks_per_sec": round(tokens / wall_s, 1) if wall_s else None,
        "usd_per_mtok": (
            round(usd_hour * (wall_s / 3600.0) / (tokens / 1e6), 4)
            if tokens else None
        ),
    }


async def run_scenario(**overrides) -> dict:
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.kv_router import (
        KvEventPublisher,
        KvMetricsPublisher,
        KvPushRouter,
        KvRouter,
    )
    from dynamo_tpu.llm.kv_router.pull import KvExportHandler, PrefixPuller
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models import config as cfgmod

    d = {**_defaults(), **overrides}
    page = d["page"]
    prefix_len = d["prefix_pages"] * page
    isl = prefix_len + d["suffix"]
    cfg = cfgmod.get_config("tiny")
    rng = np.random.RandomState(7)

    def engine_config() -> EngineConfig:
        return EngineConfig(
            model=cfg, dtype="float32", page_size=page,
            num_pages=d["num_pages"], max_batch_size=d["max_batch"],
            max_model_len=isl + d["hold_osl"] + 32,
            prefill_chunk=isl,
            # the scenario scores routing/transfer economics, not
            # kernels — the gather oracle runs identically on CPU CI
            # and on-TPU bench rigs
            attn_backend="gather",
            kv_quantization=d["kv_quant"],
        )

    hub = HubServer()
    await hub.start("127.0.0.1", 0)
    hub_addr = f"127.0.0.1:{hub.port}"
    eid = EndpointId(NS, COMP, EP)
    pull_counters0 = {
        k: counters.get(k)
        for k in ("kv_pull_decisions_total", "kv_pull_landed_total",
                  "kv_pull_tokens_total", "kv_pull_failed_total")
    }

    drts, engines, pullers = [], [], []
    wids: list[int] = []           # engine index -> hub worker id
    served: dict[str, int] = {}   # request_id -> worker index
    ledgers: dict[str, dict] = {}  # request_id -> prefix ledger
    tokens_served: list[int] = [0]
    try:
        for i in range(2):
            drt = await DistributedRuntime.from_settings(hub_addr=hub_addr)
            drts.append(drt)
            wids.append(drt.primary_lease.lease_id)
            engine = JaxEngine(engine_config())
            engines.append(engine)

            def _observe(summary, i=i):
                served[summary["request_id"]] = i
                ledgers[summary["request_id"]] = summary.get("prefix") or {}
                tokens_served[0] += (
                    (summary.get("prompt_tokens") or 0)
                    + (summary.get("tokens") or 0)
                )

            engine.subscribe_requests(_observe)
            ep = drt.namespace(NS).component(COMP).endpoint(EP)
            KvEventPublisher(
                ep.component, drt.primary_lease.lease_id
            ).attach(engine).start()
            await KvExportHandler(drt, engine, NS, COMP).start()
            puller = PrefixPuller(drt, engine, engine, eid)
            pullers.append(puller)
            metrics = KvMetricsPublisher.for_engine(engine)
            await ep.serve_engine(
                puller, stats_handler=metrics.stats_handler
            )

        rdrt = await DistributedRuntime.from_settings(hub_addr=hub_addr)
        drts.append(rdrt)
        ep = rdrt.namespace(NS).component(COMP).endpoint(EP)
        client = await ep.client()
        for _ in range(200):
            if len(client.instance_ids()) >= 2:
                break
            await asyncio.sleep(0.05)
        router = KvRouter(
            ep.component, client, block_size=page,
            poll_interval=d["poll_interval"],
            pull_threshold_tokens=d["pull_threshold_pages"] * page,
        )
        await router.start()
        push = KvPushRouter(client, router)

        prefixes = [
            rng.randint(1, cfg.vocab_size, size=prefix_len).tolist()
            for _ in range(d["tenants"])
        ]

        async def serve(tenant: int, rec: dict, osl: int) -> str:
            tokens = prefixes[tenant] + rng.randint(
                1, cfg.vocab_size, size=d["suffix"]
            ).tolist()
            pre = PreprocessedRequest(
                token_ids=tokens,
                stop_conditions=StopConditions(
                    max_tokens=osl, ignore_eos=True
                ),
                sampling_options=SamplingOptions(greedy=True),
            )
            ctx = Context(pre.to_dict())
            t0 = time.perf_counter()
            ticks = []
            async for frame in await push.generate(pre.to_dict(), context=ctx):
                if frame.get("token_ids"):
                    ticks.append(time.perf_counter())
            rec["ttft"] = ticks[0] - t0
            rec["request_id"] = ctx.id
            rec["tenant"] = tenant
            return ctx.id

        # compile warmup: serve one sacrificial random prompt per worker
        # DIRECT to its engine (cold-path prefill/decode families) and
        # re-serve it (warm continuation family) — the measured phases
        # must compare compute, not the jit compiler
        for engine in engines:
            wp = rng.randint(1, cfg.vocab_size, size=isl).tolist()
            for _ in range(2):
                pre = PreprocessedRequest(
                    token_ids=wp,
                    stop_conditions=StopConditions(
                        max_tokens=d["osl"], ignore_eos=True
                    ),
                    sampling_options=SamplingOptions(greedy=True),
                )
                async for _ in await engine.generate(Context(pre.to_dict())):
                    pass

        t_total0 = time.perf_counter()
        tok_total0 = tokens_served[0]  # warmup tokens stay OUT of the
        # headline dollars line: its wall starts here too
        tok0 = tok_total0

        # ---- phase 1: cold — every tenant's first serve, nothing
        # cached. SEQUENTIAL serving in both measured phases: the two
        # tiny workers have max_batch slots each, and a concurrent
        # gather would fold queue-wait noise into the TTFT comparison
        cold_recs = [dict() for _ in range(d["tenants"] * d["cold_per_tenant"])]
        t0 = time.perf_counter()
        for r in range(d["cold_per_tenant"]):
            for t in range(d["tenants"]):
                await serve(t, cold_recs[r * d["tenants"] + t], d["osl"])
        cold_wall = time.perf_counter() - t0
        cold_tokens = tokens_served[0] - tok0
        holder = {  # tenant -> worker index that served it cold
            rec["tenant"]: served.get(rec["request_id"])
            for rec in cold_recs
        }

        # events propagate into the router's radix index before warm
        want_blocks = d["tenants"] * d["prefix_pages"]
        for _ in range(200):
            if router.indexer.tree.num_blocks >= want_blocks:
                break
            await asyncio.sleep(0.05)

        # ---- phase 2: warm — fresh suffixes on the same prefixes; the
        # router must send each tenant back to its holder
        tok0 = tokens_served[0]
        warm_recs = [
            dict() for _ in range(d["tenants"] * d["warm_per_tenant"])
        ]
        t0 = time.perf_counter()
        for r in range(d["warm_per_tenant"]):
            for t in range(d["tenants"]):
                await serve(t, warm_recs[r * d["tenants"] + t], d["osl"])
        warm_wall = time.perf_counter() - t0
        warm_tokens = tokens_served[0] - tok0
        to_holder = sum(
            1 for rec in warm_recs
            if served.get(rec["request_id"]) == holder.get(rec["tenant"])
        )
        warm_reused = sum(
            1 for rec in warm_recs
            if (ledgers.get(rec["request_id"], {}).get("reused_blocks", 0)
                + ledgers.get(rec["request_id"], {}).get(
                    "restored_blocks", 0)) > 0
        )

        # ---- phase 3: pull — saturate one tenant's holder; new
        # requests for it must land on the idle worker via a prefix PULL
        # instead of a recompute
        victim_tenant = 0
        hold_idx = holder.get(victim_tenant) or 0
        hold_engine = engines[hold_idx]

        async def hold_one():
            pre = PreprocessedRequest(
                token_ids=rng.randint(1, cfg.vocab_size, size=isl).tolist(),
                stop_conditions=StopConditions(
                    max_tokens=d["hold_osl"], ignore_eos=True
                ),
                sampling_options=SamplingOptions(greedy=True),
            )
            async for _ in await hold_engine.generate(Context(pre.to_dict())):
                pass

        held = [
            asyncio.create_task(hold_one()) for _ in range(d["max_batch"])
        ]
        # the aggregator must SEE the saturation (scrape cadence) before
        # the pull-phase requests are scheduled
        for _ in range(100):
            m = router.aggregator.current.endpoints.get(wids[hold_idx])
            if m is not None and m.request_active_slots >= d["max_batch"]:
                break
            await asyncio.sleep(d["poll_interval"] / 2)
        pull_recs = [dict() for _ in range(d["pull_requests"])]
        t0 = time.perf_counter()
        for rec in pull_recs:
            await serve(victim_tenant, rec, d["osl"])
        pull_wall = time.perf_counter() - t0
        await asyncio.gather(*held)

        total_wall = time.perf_counter() - t_total0
        total_tokens = tokens_served[0] - tok_total0
        usd = d["chip_hour_usd"]

        def p50(recs):
            return round(
                float(np.percentile([r["ttft"] for r in recs], 50)), 4
            )

        pulls = {
            k[len("kv_pull_"):-len("_total")]: int(
                counters.get(k) - pull_counters0[k]
            )
            for k in pull_counters0
        }
        pulls["tokens_moved"] = sum(p.pull_tokens for p in pullers)
        # zero-orphan quiesce census (engine/kv_ledger.py): every page
        # the phases touched must be back to free/cached custody before
        # teardown — a leak here fails the bench, not just a dashboard
        census = await asyncio.to_thread(quiesce_census, engines)
        return {
            "scenario": {
                k: d[k]
                for k in ("tenants", "page", "prefix_pages", "suffix",
                          "osl", "warm_per_tenant", "pull_requests",
                          "max_batch")
                # kv_quant joins the descriptor ONLY when set: the
                # bench-history context must stay byte-identical for
                # the existing unquantized baselines
            } | ({"kv_quant": d["kv_quant"]} if d["kv_quant"] else {}),
            "ttft_cold_p50_s": p50(cold_recs),
            "ttft_warm_p50_s": p50(warm_recs),
            "ttft_pull_p50_s": p50(pull_recs),
            "warm_vs_cold_ttft": round(
                p50(cold_recs) / p50(warm_recs), 3
            ),
            "route_to_holder_frac": round(to_holder / len(warm_recs), 3),
            "warm_reuse_frac": round(warm_reused / len(warm_recs), 3),
            "router_blocks": router.indexer.tree.num_blocks,
            "pulls": pulls,
            "dollars": {
                "chip_hour_usd": usd,
                "cold": _phase_dollars(cold_tokens, cold_wall, usd),
                "warm": _phase_dollars(warm_tokens, warm_wall, usd),
                "pull_phase_wall_s": round(pull_wall, 4),
                **_phase_dollars(total_tokens, total_wall, usd),
            },
            "kv_census": census,
        }
    finally:
        for e in engines:
            try:
                await e.close()
            except Exception:  # noqa: BLE001
                pass
        for drt in drts:
            try:
                await drt.shutdown()
            except Exception:  # noqa: BLE001
                pass
        await hub.stop()


def run(**overrides) -> dict:
    return asyncio.run(run_scenario(**overrides))


if __name__ == "__main__":
    out = run()
    print(json.dumps(out, indent=2))
    ok = (
        out["warm_reuse_frac"] > 0
        and out["pulls"]["landed"] >= 1
        and out["router_blocks"] > 0
        and out["kv_census"]["ok"]
    )
    sys.exit(0 if ok else 1)
