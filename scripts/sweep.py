"""Concurrency-sweep serving benchmark — the reference's protocol
(reference: examples/llm/benchmarks/README.md:27-34 — genai-perf sweep,
concurrency 1..256) against the local chip. Reuses bench.py's engine
setup per point; writes SWEEP.json at the repo root and prints a table.

Run: python scripts/sweep.py [conc ...]   (default 1 4 16 64 128)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_point(conc: int) -> dict:
    # prepend (not replace) PYTHONPATH: the platform plugin may register
    # through an existing PYTHONPATH entry
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(
        os.environ,
        BENCH_CONCURRENCY=str(conc),
        BENCH_FAST="1",  # headline + prefix probe per point
        PYTHONPATH=f"{REPO}:{pp}" if pp else REPO,
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=2400,
    )
    lines = [ln for ln in out.stdout.strip().splitlines() if ln.startswith("{")]
    if not lines:
        raise RuntimeError(
            f"bench conc={conc} produced no JSON (rc={out.returncode}):\n"
            f"{out.stdout[-2000:]}\n{out.stderr[-2000:]}"
        )
    return json.loads(lines[-1])


def main() -> None:
    concs = [int(a) for a in sys.argv[1:]] or [1, 4, 16, 64, 128]
    points = []
    print(f"{'conc':>5} {'decode tok/s':>13} {'total tok/s':>12} "
          f"{'p50 TTFT s':>11} {'p50 ITL ms':>11}")
    for conc in concs:
        r = run_point(conc)
        e = r["extra"]
        points.append({
            "concurrency": conc,
            "decode_toks_per_s_chip": r["value"],
            "total_toks_per_s_chip": e["total_toks_per_sec_chip"],
            "p50_ttft_s": e["p50_ttft_s"],
            "p50_itl_s": e["p50_itl_s"],
            "vs_baseline": r["vs_baseline"],
        })
        print(f"{conc:>5} {r['value']:>13.1f} "
              f"{e['total_toks_per_sec_chip']:>12.1f} "
              f"{e['p50_ttft_s']:>11.3f} {e['p50_itl_s'] * 1e3:>11.2f}")
    extra = {}
    sweep_path = os.path.join(REPO, "SWEEP.json")
    if os.path.exists(sweep_path):
        try:
            prev = json.load(open(sweep_path))
            extra = {
                k: v for k, v in prev.items()
                if k not in ("metric", "protocol", "points")
            }
        except Exception:
            pass
    record = {
        **extra,
        "metric": points and points[-1] or {},
        "protocol": {
            "isl": int(os.environ.get("BENCH_ISL", "512")),
            "osl": int(os.environ.get("BENCH_OSL", "64")),
            "quant": os.environ.get("BENCH_QUANT", "int8"),
        },
        "points": points,
    }
    with open(os.path.join(REPO, "SWEEP.json"), "w") as f:
        json.dump(record, f, indent=1)
    print("wrote SWEEP.json")


if __name__ == "__main__":
    main()
