"""Serving benchmark on the local TPU chip — prints ONE JSON line.

Protocol (scaled-down from the reference's genai-perf sweep, BASELINE.md:
ISL 3000 / OSL 150, concurrency sweep): N concurrent requests with a fixed
ISL/OSL through the full engine (continuous batching, paged KV, on-device
sampling); measures steady-state decode throughput per chip plus p50
TTFT/ITL.

Baseline for `vs_baseline`: the north star is tokens/sec/chip parity with
vLLM on H100 for Llama-3.1-8B (BASELINE.json). We take 2000 tok/s/GPU as
the parity bar for 8B-class decode throughput and scale it by relative
parameter count when a smaller preset is benched (smaller chips can't hold
8B in bf16), so the ratio stays comparable across rounds and chip types.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

import numpy as np

PARITY_8B_TOKS_PER_CHIP = 2000.0
_8B_PARAMS = 8.03e9

ISL = int(os.environ.get("BENCH_ISL", "512"))
OSL = int(os.environ.get("BENCH_OSL", "64"))
CONCURRENCY = int(os.environ.get("BENCH_CONCURRENCY", "256"))
DECODE_STEPS = int(os.environ.get("BENCH_DECODE_STEPS", "16"))
PREFILL_GROUP = int(os.environ.get("BENCH_PREFILL_GROUP", "32768"))
# int8 W8A8 serving is the default protocol: the reference's baselines
# serve FP8 on H100 (BASELINE.md "70B FP8"), so the quantized path is the
# apples-to-apples configuration. BENCH_QUANT=none for bf16.
QUANT = os.environ.get("BENCH_QUANT", "int8")
QUANT = None if QUANT in ("", "none") else QUANT


def main() -> None:
    import jax

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.pipeline.context import Context

    import __graft_entry__

    cfg = __graft_entry__._pick_config()
    n_chips = len(jax.local_devices())

    engine = JaxEngine(
        EngineConfig(
            model=cfg,
            dtype="bfloat16",
            max_batch_size=CONCURRENCY,
            max_model_len=ISL + OSL + 32,
            prefill_chunk=ISL,
            decode_steps=DECODE_STEPS,
            prefill_group_tokens=PREFILL_GROUP,
            quantization=QUANT,
        )
    )
    n_params = engine.param_count

    rng = np.random.RandomState(0)
    prompts = [
        rng.randint(1, cfg.vocab_size, size=ISL).tolist() for _ in range(CONCURRENCY)
    ]

    async def one(prompt, record):
        pre = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=OSL, ignore_eos=True),
            sampling_options=SamplingOptions(greedy=True),
        )
        t0 = time.perf_counter()
        ticks = []
        async for frame in await engine.generate(Context(pre.to_dict())):
            if frame.get("token_ids"):
                ticks.append(time.perf_counter())
        record["ttft"] = ticks[0] - t0
        # Effective ITL: tokens arrive in multi-step bursts, so intra-burst
        # frame diffs are meaningless — report the per-request average
        # token-to-token latency over the whole decode instead.
        record["itl"] = (
            (ticks[-1] - ticks[0]) / (len(ticks) - 1) if len(ticks) > 1 else None
        )
        record["tokens"] = len(ticks)

    async def run():
        # warmup at FULL concurrency so every compiled shape family
        # (prefill group sizes, decode batch) is built before measuring;
        # distinct prompts so no measured request rides the prefix cache.
        # TWO waves: admission timing varies between waves, so the set of
        # prefill-group row counts (power-of-two families) a wave hits is
        # not deterministic — one wave can leave a family uncompiled
        for _ in range(2):
            warm_prompts = [
                rng.randint(1, cfg.vocab_size, size=ISL).tolist()
                for _ in range(CONCURRENCY)
            ]
            await asyncio.gather(*(one(p, {}) for p in warm_prompts))
        t0 = time.perf_counter()
        records = [dict() for _ in prompts]
        await asyncio.gather(*(one(p, r) for p, r in zip(prompts, records)))
        wall = time.perf_counter() - t0

        # prefix-cache TTFT probe (BASELINE.md: KV-aware routing's 3x TTFT
        # win comes from prefix hits): identical prompt twice, idle engine
        probe = rng.randint(1, cfg.vocab_size, size=ISL).tolist()
        cold, warm = {}, {}
        await one(probe, cold)
        await one(probe, warm)
        return records, wall, cold["ttft"] / warm["ttft"]

    records, wall, prefix_speedup = asyncio.run(run())
    total_tokens = sum(r["tokens"] for r in records)
    toks_per_sec_chip = total_tokens / wall / n_chips
    ttft_p50 = float(np.percentile([r["ttft"] for r in records], 50))
    itls = [r["itl"] for r in records if r["itl"] is not None]
    itl_p50 = float(np.percentile(itls, 50)) if itls else 0.0

    target = PARITY_8B_TOKS_PER_CHIP * (_8B_PARAMS / n_params)
    print(
        json.dumps(
            {
                "metric": f"{cfg.name}{f' {QUANT}' if QUANT else ''} serving "
                f"decode throughput (ISL={ISL} OSL={OSL} conc={CONCURRENCY})",
                "value": round(toks_per_sec_chip, 2),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(toks_per_sec_chip / target, 4),
                "extra": {
                    "p50_ttft_s": round(ttft_p50, 4),
                    "p50_itl_s": round(itl_p50, 6),
                    "chips": n_chips,
                    "params": n_params,
                    "parity_target_toks_per_chip": round(target, 1),
                    # the wall includes prefilling ISL tokens per request;
                    # total token throughput shows the full device output
                    "prefill_toks_per_sec_chip": round(
                        CONCURRENCY * ISL / wall / n_chips, 1
                    ),
                    "total_toks_per_sec_chip": round(
                        (CONCURRENCY * ISL + total_tokens) / wall / n_chips, 1
                    ),
                    # cold/warm TTFT on an identical prompt (prefix cache)
                    "prefix_hit_ttft_speedup": round(prefix_speedup, 2),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
