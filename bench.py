"""Serving benchmark on the local TPU chip — prints ONE JSON line.

Protocol (scaled-down from the reference's genai-perf sweep, BASELINE.md:
ISL 3000 / OSL 150, concurrency sweep): N concurrent requests with a fixed
ISL/OSL through the full engine (continuous batching, paged KV, on-device
sampling); measures steady-state decode throughput per chip plus p50
TTFT/ITL.

Baseline for `vs_baseline`: the north star is tokens/sec/chip parity with
vLLM on H100 for Llama-3.1-8B (BASELINE.json), 2000 tok/s/GPU. With int8
weights the REAL 8B model fits the 16 GB v5e chip and is benched against
that bar UNSCALED; only when a smaller preset must be used (bf16 runs) is
the bar scaled by relative parameter count so the ratio stays comparable.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

import numpy as np

PARITY_8B_TOKS_PER_CHIP = 2000.0
_8B_PARAMS = 8.03e9

ISL = int(os.environ.get("BENCH_ISL", "512"))
OSL = int(os.environ.get("BENCH_OSL", "64"))
DECODE_STEPS = int(os.environ.get("BENCH_DECODE_STEPS", "16"))
# int8 W8A8 weights + int8 KV pages are the default protocol: the
# reference's baselines serve FP8 on H100 (BASELINE.md "70B FP8"), so the
# fully-quantized path is the apples-to-apples configuration — and it is
# what fits the real 8B north-star model on a 16 GB v5e chip.
# BENCH_QUANT=none / BENCH_KV_QUANT=none for bf16 variants.
QUANT = os.environ.get("BENCH_QUANT", "int8")
QUANT = None if QUANT in ("", "none") else QUANT
KV_QUANT = os.environ.get("BENCH_KV_QUANT", "int8")
KV_QUANT = None if KV_QUANT in ("", "none") else KV_QUANT
# int8-KV pallas kernels put page tokens in lanes (page 128); bf16 runs
# use 64-token pages — fixed here because the PREFIX PROBE must know it
PAGE_SIZE = 128 if KV_QUANT else 64
# prefix-probe prompt length: at least 2 full pages + a partial tail
# regardless of BENCH_ISL. A prompt shorter than one page has NO
# cacheable block, so its "warm" serve reuses nothing and the reported
# speedup is pure tunnel noise — exactly how BENCH_r06 (ISL=64, page
# 128) printed the phantom 0.68x "regression". The engine config below
# sizes prefill_chunk/max_model_len to cover this.
PROBE_ISL = max(ISL, 2 * PAGE_SIZE + PAGE_SIZE // 2)
# BENCH_FAST=1: headline wave + prefix probe only (the concurrency sweep
# runs one engine init per point — skip the paced/offload/phase extras)
FAST = os.environ.get("BENCH_FAST", "") not in ("", "0")
# BENCH_SPEC=1: self-speculative decoding A/B — a repetitive-text wave
# served with spec off then on (same engine, runtime toggle), recording
# acceptance rate, effective tokens-per-verify-step and the tok/s delta.
# NOTE: spec_decode is incompatible with the packed pallas+int8 KV pools
# (the engine refuses at init) — on TPU run it with BENCH_KV_QUANT=none.
SPEC = os.environ.get("BENCH_SPEC", "") not in ("", "0")
SPEC_K = int(os.environ.get("BENCH_SPEC_K", "4"))
SPEC_NGRAM = int(os.environ.get("BENCH_SPEC_NGRAM", "3"))
SPEC_OSL = int(os.environ.get("BENCH_SPEC_OSL", str(max(OSL, 128))))
# BENCH_MIXED=1: stall-free mixed batching A/B — hold N streams in
# steady decode, inject an admission wave of fresh prompts, and record
# the held streams' decode ITL p50/p99 DURING the wave plus the wave's
# TTFT, mixed batching off then on (runtime toggle, same engine).
# NOTE: mixed batching is incompatible with the packed pallas+int8 KV
# pools (the engine degrades to normal paths and the A/B reads ~1x) —
# on TPU run it with BENCH_KV_QUANT=none.
MIXED = os.environ.get("BENCH_MIXED", "") not in ("", "0")
MIXED_TOKENS = int(os.environ.get("BENCH_MIXED_TOKENS", "1024"))
MIXED_HELD = int(os.environ.get("BENCH_MIXED_HELD", "8"))
MIXED_WAVE = int(os.environ.get("BENCH_MIXED_WAVE", "16"))
MIXED_OSL = int(os.environ.get("BENCH_MIXED_OSL", str(max(OSL, 128))))
# BENCH_PIPELINE=1: step-pipeline A/B — the same held+wave mixed cycle
# run serialized (EngineConfig.step_pipeline=False: every step is
# dispatch -> fetch -> sync) then pipelined, reporting the sync-fetch
# wall (`mixed_sync_s + decode_sync_s`) as a fraction of the total
# dispatch+sync step wall. Also runs whenever BENCH_MIXED=1 is set.
PIPE = MIXED or os.environ.get("BENCH_PIPELINE", "") not in ("", "0")
# BENCH_PREFIX_FLEET=1: multi-tenant shared-prefix FLEET scenario
# (scripts/prefix_fleet.py) — in-process hub + two real workers + the
# KV-aware router with live engine events, scoring warm-vs-cold TTFT
# across the fleet, route-to-holder rate, cross-worker prefix pulls
# (saturated holder -> export/ingest transfer instead of recompute) and
# $-per-million-tokens. Emits the `prefix_fleet` BENCH_OUT section.
PREFIX_FLEET = os.environ.get("BENCH_PREFIX_FLEET", "") not in ("", "0")
# BENCH_CONTROL=1: chaos-controller scenario (scripts/control_chaos.py)
# — spawn a real hub + supervisor-managed worker pool, inject a load
# spike + DYN_FAULTS worker death, and score the SLO-driven planner on
# the attainment recovery curve (time-to-recover, goodput retained,
# graceful lease-revoke drain). Pure control-plane: no model, runs the
# same at any BENCH_MODEL. Emits the `control` BENCH_OUT section.
CONTROL = os.environ.get("BENCH_CONTROL", "") not in ("", "0")
# BENCH_FAILOVER=1: request-failover chaos scenario
# (scripts/failover_chaos.py) — in-process hub + real workers + the
# journaled failover plane; worker.die severs the serving data plane
# mid-stream and every greedy SSE stream must complete byte-identical.
# Scores recovered_frac, the replay TTFT gap, and the continuation
# economics (recompute vs cache-reuse vs cross-worker pull). Emits the
# `failover` BENCH_OUT section.
FAILOVER = os.environ.get("BENCH_FAILOVER", "") not in ("", "0")
# BENCH_KV_CAPACITY=1: KV-tier capacity census (scripts/kv_capacity.py)
# — bf16/int8/int4 page bytes measured off live pools, max resident
# streams at a fixed byte budget (BENCH_KV_CAPACITY_MB), a saturating
# decode wave per quantized tier, and the margin-stable greedy
# token-match quality bound vs the f32-KV reference. Emits the
# `kv_capacity` BENCH_OUT section; spawns its own tiny engines, so it
# runs the same at any BENCH_MODEL.
KV_CAPACITY = os.environ.get("BENCH_KV_CAPACITY", "") not in ("", "0")
KV_CAPACITY_MB = float(os.environ.get("BENCH_KV_CAPACITY_MB", "64"))
# BENCH_TP_OVERLAP=1: TP comm/compute overlap ledger
# (scripts/tp_overlap_bench.py) — per-layer step wall serialized-psum vs
# the ring executor (parallel/tp_overlap.py) plus the measured
# collective-byte ledger: exposed bytes EXACTLY 0.5x, total wire bytes
# conserved, greedy argmax byte-identical to tp=1. Runs as a SUBPROCESS
# (it needs its own 8-virtual-device CPU mesh, and this process already
# initialized jax against the real backend); emits the `tp_overlap`
# BENCH_OUT section. Independent of BENCH_MODEL.
TP_OVERLAP = os.environ.get("BENCH_TP_OVERLAP", "") not in ("", "0")
# BENCH_SCENARIOS=1: trace-driven scenario suite (dynamo_tpu/loadgen/,
# docs/loadgen.md) — one seeded open-loop scenario per workload the
# engine supports (chat, rag, shared-prefix, bursty+admission,
# long-context ring, MoE, vision, structured sampling), each scored by
# the SLO-gated goodput machinery. Scenario engines are built at
# LOADGEN_SCALE (default tiny), INDEPENDENT of the headline model — so
# one invocation can bench the REAL-model headline and still run the
# tiny scenario suite (the r06 mistake was conflating the two).
SCENARIOS = os.environ.get("BENCH_SCENARIOS", "") not in ("", "0")
# BENCH_OUT=path: ALSO write a machine-readable JSON results file with
# every section keyed separately (headline, spec, mixed, mixed_spec) —
# the stdout line stays the one-line headline artifact. Downstream
# trajectory tooling parses the file, not stdout.
BENCH_OUT = os.environ.get("BENCH_OUT", "")
# SLO target for the goodput section: tokens only count as "good" when
# their request's client TTFT met this budget — throughput that blows
# the latency target is not serving capacity (goodput accounting,
# docs/observability.md "Fleet plane")
SLO_TTFT = float(os.environ.get("BENCH_SLO_TTFT", "2.0"))
# BENCH_TRACE=path: arm the span recorder (dynamo_tpu/utils/tracing.py)
# for the whole run and dump Chrome/Perfetto trace-event JSON there at
# exit — request spans (submit->finish) plus the engine step timeline
# (prefill/decode/mixed/spec_verify dispatches with rows/tokens/walls).
# Load the file at https://ui.perfetto.dev (docs/observability.md).
BENCH_TRACE = os.environ.get("BENCH_TRACE", "")

ENV_HELP = """bench.py — serving benchmark; configuration via env vars:
  BENCH_MODEL                  preset override (auto-picked from HBM)
  BENCH_ISL / BENCH_OSL        input/output sequence lengths (512 / 64)
  BENCH_DECODE_STEPS           decode steps per jit dispatch (16)
  BENCH_QUANT                  weights quant: int8|none (int8)
  BENCH_KV_QUANT               KV cache quant: int8|int4|none (int8);
                               int4 nibble-packs two values per pool
                               byte — quarter of bf16's KV bytes
                               (docs/kv_cache.md "int4 packed tier")
  BENCH_FAST=1                 headline wave + prefix probe only
  BENCH_CONCURRENCY            concurrent requests (128 big / 256 small)
  BENCH_PREFILL_GROUP          prefill group token budget
  BENCH_HOST_KV_PAGES          host offload tier pages (16)
  BENCH_PREFILL_WINDOW         admission batching window seconds (0.25)
  BENCH_REPS                   measured-wave repetitions (3)
  BENCH_PACED_FRAC(_HI)        paced-arrival operating points (0.35/0.5)
  BENCH_SPEC=1                 speculative-decode A/B (off by default)
  BENCH_SPEC_K                 drafted tokens per verify step (4)
  BENCH_SPEC_NGRAM             longest proposer n-gram (3)
  BENCH_SPEC_OSL               output length of the spec A/B waves
                               (max(BENCH_OSL, 128))
  BENCH_SPEC_CONC              concurrency of the spec A/B waves (32)
  BENCH_MIXED=1                mixed-batching A/B: held-decode ITL
                               p50/p99 during an admission wave, mixed
                               off vs on (off by default; on TPU pair
                               with BENCH_KV_QUANT=none — packed int8
                               pools cannot run mixed steps)
  BENCH_MIXED_TOKENS           mixed step token budget (1024)
  BENCH_MIXED_HELD             streams held in steady decode (8)
  BENCH_MIXED_WAVE             admission-wave prompt count (16)
  BENCH_MIXED_OSL              held streams' output length
                               (max(BENCH_OSL, 128))
  BENCH_PIPELINE=1             step-pipeline A/B: the held+wave mixed
                               cycle serialized (step_pipeline=False)
                               vs pipelined — sync-fetch wall as a
                               fraction of the step wall (also runs
                               whenever BENCH_MIXED=1)
  BENCH_OUT                    path: write a machine-readable JSON file
                               with every section's numbers keyed as
                               {headline, spec, mixed, mixed_spec,
                               pipeline_ab, prefix_ab, prefix_fleet,
                               control, failover, kv_capacity,
                               scenarios, goodput} (sections not run are
                               null; goodput + prefix_ab always
                               present: SLO-gated throughput, the
                               per-request prefix/offload ledgers and
                               the cold/warm counter breakdown of the
                               probes); stdout keeps the one-line
                               headline artifact
  BENCH_PREFIX_FLEET=1         multi-tenant shared-prefix FLEET
                               scenario: in-process hub + two real
                               workers + the KV-aware router fed live
                               engine events — warm-vs-cold TTFT,
                               route-to-holder rate, cross-worker
                               prefix pulls, $-per-M-tokens (adds the
                               `prefix_fleet` BENCH_OUT section;
                               scripts/prefix_fleet.py)
  BENCH_CHIP_HOUR_USD          $/chip-hour for the fleet scenario's
                               $-per-million-tokens line (1.20)
  BENCH_CONTROL=1              chaos-controller scenario: worker death +
                               load spike scored on SLO-attainment
                               recovery (adds the `control` BENCH_OUT
                               section; scripts/control_chaos.py)
  BENCH_FAILOVER=1             request-failover chaos scenario: a
                               worker.die mid-stream must resume every
                               greedy SSE stream byte-identical —
                               recovered_frac, replay TTFT gap,
                               recompute-vs-reuse-vs-pull tokens (adds
                               the `failover` BENCH_OUT section;
                               scripts/failover_chaos.py)
  BENCH_KV_CAPACITY=1          KV-tier capacity census: bf16/int8/int4
                               page bytes off live pools + max resident
                               streams at a fixed budget, per-tier
                               decode waves, and the margin-stable
                               greedy token-match quality bound (adds
                               the `kv_capacity` BENCH_OUT section;
                               scripts/kv_capacity.py)
  BENCH_KV_CAPACITY_MB         census byte budget in MiB (64)
  BENCH_TP_OVERLAP=1           TP comm/compute overlap ledger: per-layer
                               step wall serialized-psum vs the ring
                               executor + measured collective bytes
                               (exposed EXACTLY 0.5x, total conserved)
                               + greedy byte-identity vs tp=1 (adds the
                               `tp_overlap` BENCH_OUT section; subprocess
                               on 8 virtual CPU devices —
                               scripts/tp_overlap_bench.py)
  BENCH_SCENARIOS=1            trace-driven scenario suite (adds the
                               `scenarios` BENCH_OUT section): seeded
                               open-loop traces replayed per workload
                               (chat, rag, shared_prefix, bursty with
                               admission+priorities, long_context ring,
                               moe, vision, structured sampling), each
                               scored by SLO-gated goodput — see
                               docs/loadgen.md
  LOADGEN_SCENARIOS            csv | default | all (all adds the
                               prefix_fleet + control_chaos adapters)
  LOADGEN_SCALE                tiny | real scenario sizing (tiny)
  LOADGEN_MODEL                real-scale scenario preset
                               (llama-3.2-1b)
  LOADGEN_SEED                 trace seed (0); same seed reproduces
                               byte-identical trace files
  LOADGEN_N / LOADGEN_RATE     requests per trace / offered req/s
  LOADGEN_TRACE_DIR            dump each scenario's trace JSONL here
  BENCH_TRACE                  path: record the whole run with the span
                               recorder (utils/tracing.py) and dump
                               Perfetto-loadable trace-event JSON there
                               (request spans + engine step timeline)
  BENCH_SLO_TTFT               goodput TTFT budget in seconds (2.0):
                               the goodput section counts a request's
                               tokens only when its TTFT met this
  (BENCH_MIXED=1 BENCH_SPEC=1 together add the COMPOSED spec x mixed
  A/B: repetitive held streams + an admission wave, mixed-only vs
  mixed+spec — ragged verify rows inside the mixed steps)
"""


def main() -> None:
    import jax

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.pipeline.context import Context

    import __graft_entry__

    if BENCH_TRACE:
        from dynamo_tpu.utils import tracing

        tracing.enable()

    cfg = __graft_entry__._pick_config(QUANT)
    if os.environ.get("BENCH_MODEL"):
        # explicit preset override (CI smokes run the tiny preset on CPU)
        from dynamo_tpu.models.config import get_config

        cfg = get_config(os.environ["BENCH_MODEL"])
    n_chips = len(jax.local_devices())
    big = cfg.name == "llama-3.1-8b"
    # 8B on a 16 GB chip: the KV pool budget (~5 GB after int8 weights)
    # holds ~128 concurrent 608-token sequences; higher concurrency would
    # thrash the allocator with preemptions instead of adding throughput
    concurrency = int(
        os.environ.get("BENCH_CONCURRENCY", "128" if big else "256")
    )
    prefill_group = int(
        os.environ.get("BENCH_PREFILL_GROUP", "16384" if big else "32768")
    )
    engine = JaxEngine(
        EngineConfig(
            model=cfg,
            dtype="bfloat16",
            max_batch_size=concurrency,
            max_model_len=max(ISL, PROBE_ISL) + max(
                OSL,
                SPEC_OSL if SPEC else 0,
                MIXED_OSL if (MIXED or PIPE) else 0,
            ) + 32,
            # prefill_chunk covers the probe prompts too, so they stay
            # single-chunk (a sub-page prefill_chunk would start later
            # chunks off page boundaries, which the pallas write path
            # refuses); for the default ISL=512 this is unchanged
            prefill_chunk=max(ISL, PROBE_ISL),
            decode_steps=DECODE_STEPS,
            prefill_group_tokens=prefill_group,
            quantization=QUANT,
            kv_quantization=KV_QUANT,
            # spec A/B: init validates the combo (packed int8 pools
            # refuse); the main protocol's random prompts never draft,
            # so the headline numbers are unaffected — the A/B flips
            # this flag per wave
            spec_decode=SPEC,
            spec_k_max=SPEC_K,
            spec_ngram_max=SPEC_NGRAM,
            # mixed-batching A/B: the flag itself is a per-tick host
            # decision toggled per wave below; only the budget is fixed
            # at init. spec COMPOSES with mixed (ragged verify rows) —
            # with both env flags set the composed A/B below toggles the
            # two flags together.
            mixed_batching=False,
            mixed_step_tokens=MIXED_TOKENS,
            # int8-KV pallas kernels put page tokens in lanes
            page_size=PAGE_SIZE,
            # HBM->host offload tier ON (the reference baselines run with
            # their multi-tier KV manager active); sized for the TTFT
            # probe, small enough to stay out of the headline's way
            host_kv_pages=int(os.environ.get("BENCH_HOST_KV_PAGES", "16")),
            # paced arrivals: briefly batch trickling admissions (A/B on
            # this rig: +38% paced throughput AND better TTFT — fewer
            # decode-plane interruptions)
            prefill_batch_window_s=float(
                os.environ.get("BENCH_PREFILL_WINDOW", "0.25")
            ),
        )
    )
    # park the offload tier outside its probe: a D2H page gather holds
    # the KV lock for the whole (tunnel-slow) copy and would serialize
    # the throughput/paced measurements
    engine.offload_paused = True
    # spec stays parked outside its own A/B too (a runtime host-side
    # toggle): tiny-vocab/random-prompt runs would otherwise draft on
    # the HEADLINE wave and muddy the baseline numbers
    engine.config.spec_decode = False
    n_params = engine.param_count

    # goodput accounting: every finished request's summary (latency +
    # the per-request prefix/offload ledger stamped at page
    # reservation) collects here; the probes below snapshot index
    # ranges to attribute ledgers to their wave — the data that finally
    # EXPLAINS a prefix-hit ratio instead of just reporting it
    summaries: list = []
    engine.subscribe_requests(summaries.append)
    goodput: dict = {}

    def ledger_agg(batch):
        pf = [s.get("prefix") or {} for s in batch]
        reasons: dict = {}
        for p in pf:
            r = p.get("gate_reason")
            if r:
                reasons[r] = reasons.get(r, 0) + 1
        return {
            "requests": len(batch),
            "reused_blocks": sum(p.get("reused_blocks", 0) for p in pf),
            "restored_blocks": sum(p.get("restored_blocks", 0) for p in pf),
            "declined_blocks": sum(p.get("declined_blocks", 0) for p in pf),
            "gate_reasons": reasons,
            # per-request rows (capped): which requests reused/restored
            # how many blocks — the request-level ledger
            "per_request": [
                {
                    "request": (s.get("request_id") or "")[:8],
                    "prompt_tokens": s.get("prompt_tokens"),
                    **(s.get("prefix") or {}),
                }
                for s in batch[:32]
            ],
        }

    rng = np.random.RandomState(0)

    async def one(prompt, record, max_tokens=OSL):
        pre = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(
                max_tokens=max_tokens, ignore_eos=True
            ),
            sampling_options=SamplingOptions(greedy=True),
        )
        t0 = time.perf_counter()
        ticks = []
        async for frame in await engine.generate(Context(pre.to_dict())):
            if frame.get("token_ids"):
                ticks.append(time.perf_counter())
            meta = frame.get("meta")
            if meta and "engine_ttft_s" in meta:
                # engine-side split (scheduler stamps): submit->dispatch-
                # returned, excludes the tunnel fetch/delivery RTT
                record["engine_ttft"] = meta["engine_ttft_s"]
                record["queue_wait"] = meta.get("queue_wait_s")
        record["ttft"] = ticks[0] - t0
        # Effective ITL: tokens arrive in multi-step bursts, so intra-burst
        # frame diffs are meaningless — report the per-request average
        # token-to-token latency over the whole decode instead.
        record["itl"] = (
            (ticks[-1] - ticks[0]) / (len(ticks) - 1) if len(ticks) > 1 else None
        )
        record["tokens"] = len(ticks)

    def _probe_ratio(cold, warm):
        return cold["ttft"] / warm["ttft"]

    async def run():
        # warmup at FULL concurrency so every compiled shape family
        # (prefill group sizes, decode batch) is built before measuring;
        # distinct prompts so no measured request rides the prefix cache.
        # TWO waves: admission timing varies between waves, so the set of
        # prefill-group row counts (power-of-two families) a wave hits is
        # not deterministic — one wave can leave a family uncompiled
        for _ in range(2):
            warm_prompts = [
                rng.randint(1, cfg.vocab_size, size=ISL).tolist()
                for _ in range(concurrency)
            ]
            await asyncio.gather(*(one(p, {}) for p in warm_prompts))
        # paced arrivals dispatch SMALL prefill groups (and small decode
        # buckets) the full-concurrency waves never hit — compile every
        # power-of-two family (rows 1..32) now or the paced phase
        # measures compiler stalls as TTFT (measured: a 40 s mid-wave
        # stall from one cold [8, 512] prefill family). FAST mode skips
        # the paced phase, so it needs none of these
        for k in (() if FAST else (1, 2, 3, 6, 12, 24, 48)):
            if k >= concurrency:
                break
            batch = [
                rng.randint(1, cfg.vocab_size, size=ISL).tolist()
                for _ in range(k)
            ]
            await asyncio.gather(*(one(p, {}) for p in batch))
        # cached-continuation shape: a prefix-cache hit prefills only the
        # final partial page — its small bucket family must be compiled
        # before the warm probe measures it
        dup = rng.randint(1, cfg.vocab_size, size=ISL).tolist()
        await one(dup, {})
        await one(dup, {})
        # ---- measured waves x3 (median-of-3: tunnel drift is ~±10% and
        # decides whether the headline reads 0.61 or 0.67); the engine's
        # phase counters are snapshotted for the raw artifact
        n_reps = 1 if FAST else int(os.environ.get("BENCH_REPS", "3"))
        ps0 = engine.phase_stats
        reps = []
        for _ in range(n_reps):
            rep_prompts = [
                rng.randint(1, cfg.vocab_size, size=ISL).tolist()
                for _ in range(concurrency)
            ]
            recs = [dict() for _ in rep_prompts]
            t0 = time.perf_counter()
            await asyncio.gather(*(one(p, r) for p, r in zip(rep_prompts, recs)))
            reps.append((time.perf_counter() - t0, recs))
        ps1 = engine.phase_stats
        phase_delta = {k: ps1[k] - ps0[k] for k in ps0}
        wall_spread = [round(r[0], 3) for r in reps]  # chronological
        reps.sort(key=lambda x: x[0])
        wall, records = reps[len(reps) // 2]  # median wall's wave

        # ---- phase split: a MEASURED prefill-only wave (OSL=1, whole-
        # wave wall — per-request RTTs overlap, and the engine-side token
        # counter confirms what it prefilled). Dispatch-call walls are
        # NOT usable as device walls (async returns through the tunnel;
        # probed: 0.125 s of calls for 196k tokens) and fencing each
        # dispatch inflates the wall with per-dispatch RTTs instead —
        # the dedicated wave is the honest measurement on this rig.
        prefill_wall = prefill_wave_tokens = None
        if not FAST:
            pf0 = engine.phase_stats
            pf_prompts = [
                rng.randint(1, cfg.vocab_size, size=ISL).tolist()
                for _ in range(concurrency)
            ]
            t1 = time.perf_counter()
            await asyncio.gather(*(one(p, {}, max_tokens=1) for p in pf_prompts))
            prefill_wall = time.perf_counter() - t1
            prefill_wave_tokens = (
                engine.phase_stats["prefill_tokens"] - pf0["prefill_tokens"]
            )

        async def spec_ab():
            """Speculative-decode A/B on a repetitive-text workload: the
            same wave greedy-served with spec_decode off, then on (the
            flag is a per-tick host decision, so a runtime toggle is
            sound). Distinct 16-token segments tiled to ISL: every
            suffix n-gram recurs within its own prompt, no cross-request
            prefix-cache hits."""
            n_spec = min(
                concurrency, int(os.environ.get("BENCH_SPEC_CONC", "32"))
            )

            def rep_prompts():
                return [
                    np.tile(
                        rng.randint(1, cfg.vocab_size, size=16),
                        SPEC_OSL // 16 + ISL // 16 + 2,
                    )[:ISL].tolist()
                    for _ in range(n_spec)
                ]

            engine.config.spec_decode = False
            # warm the off-wave compile families (small-row prefill
            # groups this concurrency may never have hit)
            await asyncio.gather(
                *(one(p, {}, max_tokens=SPEC_OSL) for p in rep_prompts()[:2])
            )
            off = rep_prompts()
            t0 = time.perf_counter()
            await asyncio.gather(
                *(one(p, {}, max_tokens=SPEC_OSL) for p in off)
            )
            wall_off = time.perf_counter() - t0
            engine.config.spec_decode = True
            # compile the verify families before measuring
            await asyncio.gather(
                *(one(p, {}, max_tokens=SPEC_OSL) for p in rep_prompts()[:2])
            )
            ps_a = engine.phase_stats
            on = rep_prompts()
            t0 = time.perf_counter()
            await asyncio.gather(
                *(one(p, {}, max_tokens=SPEC_OSL) for p in on)
            )
            wall_on = time.perf_counter() - t0
            ps_b = engine.phase_stats
            engine.config.spec_decode = False
            d = {k: ps_b[k] - ps_a[k] for k in ps_a}
            toks = n_spec * SPEC_OSL
            return {
                "k_max": SPEC_K,
                "ngram_max": SPEC_NGRAM,
                "concurrency": n_spec,
                "osl": SPEC_OSL,
                "acceptance_rate": (
                    round(d["spec_accepted"] / d["spec_drafted"], 4)
                    if d["spec_drafted"] else None
                ),
                "effective_tokens_per_step": (
                    round(d["spec_emitted"] / d["spec_rows"], 3)
                    if d["spec_rows"] else None
                ),
                "verify_steps": d["spec_dispatches"],
                "toks_per_sec_chip_off": round(toks / wall_off / n_chips, 1),
                "toks_per_sec_chip_on": round(toks / wall_on / n_chips, 1),
                "speedup": round(wall_off / wall_on, 3),
            }

        async def held_one(prompt, record):
            pre = PreprocessedRequest(
                token_ids=prompt,
                stop_conditions=StopConditions(
                    max_tokens=MIXED_OSL, ignore_eos=True
                ),
                sampling_options=SamplingOptions(greedy=True),
            )
            # bind the LIVE list before streaming: the wave launcher
            # polls it to detect steady decode
            ticks = record["ticks"] = []
            async for frame in await engine.generate(
                Context(pre.to_dict())
            ):
                if frame.get("token_ids"):
                    ticks.append(time.perf_counter())

        def mixed_prompts(k, repetitive=False):
            if repetitive:
                # distinct 16-token segments tiled: every suffix n-gram
                # recurs within its own prompt (draftable), no
                # cross-request prefix-cache hits
                return [
                    np.tile(
                        rng.randint(1, cfg.vocab_size, size=16),
                        MIXED_OSL // 16 + ISL // 16 + 2,
                    )[:ISL].tolist()
                    for _ in range(k)
                ]
            return [
                rng.randint(1, cfg.vocab_size, size=ISL).tolist()
                for _ in range(k)
            ]

        async def mixed_wave(mixed_on, spec_on=False, repetitive_held=False):
            """One held+wave cycle: MIXED_HELD streams in steady decode,
            then MIXED_WAVE fresh prompts as one admission wave; returns
            the held streams' inter-token gaps DURING the wave (p50/p99
            — the p99 IS the admission stall) and the wave's TTFT."""
            engine.config.mixed_batching = mixed_on
            engine.config.spec_decode = spec_on
            held_recs = [dict() for _ in range(MIXED_HELD)]
            t_all0 = time.perf_counter()
            tasks = [
                asyncio.create_task(held_one(p, r))
                for p, r in zip(
                    mixed_prompts(MIXED_HELD, repetitive_held), held_recs
                )
            ]
            # wait for steady decode: every held stream past its
            # first few tokens before the wave lands. A held task
            # dying here would otherwise spin this poll forever —
            # surface its error instead.
            while not all(
                len(r.get("ticks", ())) >= 4 for r in held_recs
            ):
                for t in tasks:
                    if t.done() and t.exception() is not None:
                        raise t.exception()
                await asyncio.sleep(0.02)
            wave_recs = [dict() for _ in range(MIXED_WAVE)]
            t_w0 = time.perf_counter()
            await asyncio.gather(*(
                one(p, r)
                for p, r in zip(mixed_prompts(MIXED_WAVE), wave_recs)
            ))
            t_w1 = time.perf_counter()
            await asyncio.gather(*tasks)
            wall_all = time.perf_counter() - t_all0
            engine.config.mixed_batching = False
            engine.config.spec_decode = False
            gaps = []
            for r in held_recs:
                ts = r["ticks"]
                for a, b in zip(ts, ts[1:]):
                    # gaps overlapping the admission-wave window
                    if b >= t_w0 and a <= t_w1:
                        gaps.append(b - a)
            toks = MIXED_HELD * MIXED_OSL + sum(
                r["tokens"] for r in wave_recs
            )

            def pct(vals, q):
                # gaps can be empty when the held streams drained
                # before the wave landed (MIXED_OSL too short for
                # this rig) — report None rather than crash
                return (
                    round(float(np.percentile(vals, q)), 4)
                    if len(vals) else None
                )

            return {
                "wave_itl_p50_s": pct(gaps, 50),
                "wave_itl_p99_s": pct(gaps, 99),
                "wave_ttft_p50_s": pct(
                    [r["ttft"] for r in wave_recs], 50
                ),
                "toks_per_sec_chip": round(toks / wall_all / n_chips, 1),
            }

        async def mixed_ab():
            """Stall-free mixed batching A/B: held streams + admission
            wave, mixed off then on. Fresh random prompts per wave: no
            prefix-cache hits, no draftable n-grams."""
            # warm both modes with a FULL held+wave cycle: mixed step
            # families ([pow2 rows, bucket] + the ragged attention path)
            # only compile when decode rows and prefill chunks actually
            # coexist — a plain warm wave never builds them, and the
            # measured ON wave would pay the compiles as fake stalls
            for on in (False, True):
                await mixed_wave(on)
            ps_a = engine.phase_stats
            off = await mixed_wave(False)
            on = await mixed_wave(True)
            ps_b = engine.phase_stats
            d = {k: ps_b[k] - ps_a[k] for k in ps_a}
            return {
                "step_tokens": MIXED_TOKENS,
                "held_streams": MIXED_HELD,
                "wave_prompts": MIXED_WAVE,
                "held_osl": MIXED_OSL,
                "off": off,
                "on": on,
                "mixed_steps": d["mixed_steps"],
                "mixed_decode_rows": d["mixed_decode_rows"],
                "mixed_prefill_tokens": d["mixed_prefill_tokens"],
                "decode_stall_saved_s": round(
                    d["mixed_decode_stall_saved_s"], 3
                ),
                "itl_p99_speedup": (
                    round(off["wave_itl_p99_s"] / on["wave_itl_p99_s"], 3)
                    if off["wave_itl_p99_s"] and on["wave_itl_p99_s"]
                    else None
                ),
            }

        async def mixed_spec_ab():
            """COMPOSED spec x mixed A/B: repetitive held streams (their
            n-grams draft, so decode rows ride the mixed steps as ragged
            1+k verify windows) + an admission wave of fresh prompts,
            mixed-only vs mixed+spec. The effective tokens per model
            step of the held rows is the spec win; the wave ITL p99
            proves composing did not reopen the admission stall."""
            for spec_on in (False, True):  # compile both families
                await mixed_wave(True, spec_on=spec_on, repetitive_held=True)
            base = await mixed_wave(True, repetitive_held=True)
            ps_m = engine.phase_stats
            comp = await mixed_wave(True, spec_on=True, repetitive_held=True)
            ps_b = engine.phase_stats
            d = {k: ps_b[k] - ps_m[k] for k in ps_b}
            return {
                "step_tokens": MIXED_TOKENS,
                "held_streams": MIXED_HELD,
                "wave_prompts": MIXED_WAVE,
                "held_osl": MIXED_OSL,
                "mixed_only": base,
                "mixed_spec": comp,
                # decode rows that rode mixed steps as verify windows
                "mixed_spec_rows": d["mixed_spec_rows"],
                "mixed_steps": d["mixed_steps"],
                "acceptance_rate": (
                    round(d["spec_accepted"] / d["spec_drafted"], 4)
                    if d["spec_drafted"] else None
                ),
                # >= 1.0; mixed-only decode rows are 1.0 by construction
                "effective_tokens_per_step": (
                    round(d["spec_emitted"] / d["spec_rows"], 3)
                    if d["spec_rows"] else None
                ),
                "itl_p99_ratio": (
                    round(
                        comp["wave_itl_p99_s"] / base["wave_itl_p99_s"], 3
                    )
                    if base["wave_itl_p99_s"] and comp["wave_itl_p99_s"]
                    else None
                ),
            }

        async def pipeline_ab():
            """Step-pipeline A/B (EngineConfig.step_pipeline): one
            held+wave mixed cycle fully SERIALIZED (every step is
            dispatch -> fetch -> sync; mixed ticks "hold" behind
            in-flight dispatches) vs pipelined (dispatch N+1 launches
            behind N; the fetch overlaps device compute). The honest
            comparison is the sync-fetch wall as a FRACTION of the
            total dispatch+sync step wall — absolute walls vary with
            how many steps each wave happens to run."""
            for on in (False, True):  # compile both paths' families
                engine.config.step_pipeline = on
                await mixed_wave(True)
            out = {}
            for key, on in (("serialized", False), ("pipelined", True)):
                engine.config.step_pipeline = on
                ps_a = engine.phase_stats
                wave = await mixed_wave(True)
                ps_b = engine.phase_stats
                d = {k: ps_b[k] - ps_a[k] for k in ps_b}
                sync = d["mixed_sync_s"] + d["decode_sync_s"]
                step = (
                    d["mixed_dispatch_s"] + d["decode_dispatch_s"]
                    + d["spec_dispatch_s"] + d["spec_sync_s"] + sync
                )
                out[key] = {
                    "mixed_sync_s": round(d["mixed_sync_s"], 4),
                    "decode_sync_s": round(d["decode_sync_s"], 4),
                    "sync_wall_s": round(sync, 4),
                    "step_wall_s": round(step, 4),
                    "sync_frac": round(sync / step, 4) if step else None,
                    # syncs whose fetch ran while another dispatch was
                    # already queued on device, and the wall they hid
                    # (counted in pipeline_overlap_s INSTEAD of the
                    # *_sync_s stall counters); overlap_frac = hidden
                    # share of the total fetch wall
                    "overlapped_syncs": d["pipeline_overlapped"],
                    "overlap_hidden_s": round(d["pipeline_overlap_s"], 4),
                    "overlap_frac": (
                        round(
                            d["pipeline_overlap_s"]
                            / (d["pipeline_overlap_s"] + sync), 4
                        )
                        if d["pipeline_overlap_s"] + sync else None
                    ),
                    "mixed_holds": d["mixed_holds"],
                    "mixed_carry_rows": d["mixed_carry_rows"],
                    "wave": wave,
                }
            engine.config.step_pipeline = True
            sf_ser = out["serialized"]["sync_frac"]
            sf_pipe = out["pipelined"]["sync_frac"]
            out["sync_frac_improved"] = (
                sf_ser is not None and sf_pipe is not None
                and sf_pipe < sf_ser
            )
            return out

        # ---- prefix-cache TTFT probe, WAVE-based, shared by FAST and
        # full runs (BASELINE.md: KV-aware routing's TTFT win comes from
        # prefix hits). Single idle requests cannot see the effect on
        # this rig — their TTFT is the tunnel fetch RTT (~0.17 s) on
        # both serves. A wave of distinct PROBE_ISL prompts served cold
        # then re-served (every full page a prefix hit) measures the
        # saved compute under real queuing, and the prefix_ab breakdown
        # (prefill/prefix/compile counter deltas per leg) makes a slow
        # warm wave ATTRIBUTABLE — reuse that didn't happen reads as
        # prefix_hits 0, a compile-contaminated leg as compile_events>0.
        AB_KEYS = (
            "prefill_dispatch_s", "prefill_tokens", "prefill_dispatches",
            "prefix_hits", "prefix_full_hits", "prefix_reused_tokens",
            "prefix_restored_tokens", "prefix_tail_tokens",
        )

        async def prefix_probe(n_probe):
            def probe_prompts():
                return [
                    rng.randint(1, cfg.vocab_size, size=PROBE_ISL).tolist()
                    for _ in range(n_probe)
                ]

            # sacrificial set A, served twice: the SECOND serve
            # dispatches [n, tail-bucket] prefill groups over full-width
            # block tables — continuation families the cold-path warmups
            # never build. Without this the measured warm wave pays ~30 s
            # remote compiles per family and every later phase measures
            # the compiler (observed: 65 s paced p50 TTFT from exactly
            # this cascade). The prefix_ab compile_events delta proves
            # per-leg whether the warmup actually covered the families.
            set_a = probe_prompts()
            await asyncio.gather(*(one(p, {}) for p in set_a))
            await asyncio.gather(*(one(p, {}) for p in set_a))
            set_b = probe_prompts()
            legs = {}
            prefix_ab = {"probe_isl": PROBE_ISL, "n_probe": n_probe}
            probe_summary = {}
            i0 = len(summaries)
            for leg in ("cold", "warm"):
                recs = [dict() for _ in range(n_probe)]
                ps_a, m_a = engine.phase_stats, engine.metrics()
                t0 = time.perf_counter()
                await asyncio.gather(
                    *(one(p, r) for p, r in zip(set_b, recs))
                )
                wall = time.perf_counter() - t0
                ps_b, m_b = engine.phase_stats, engine.metrics()
                i1 = len(summaries)
                ttft = float(np.percentile([r["ttft"] for r in recs], 50))
                legs[leg] = {"ttft": ttft, "wall": wall}
                prefix_ab[leg] = {
                    "ttft_p50_s": round(ttft, 4),
                    "wall_s": round(wall, 4),
                    **{
                        k: (
                            round(ps_b[k] - ps_a[k], 4)
                            if isinstance(ps_b[k], float)
                            else ps_b[k] - ps_a[k]
                        )
                        for k in AB_KEYS
                    },
                    "compile_events": (
                        m_b["compile_events"] - m_a["compile_events"]
                    ),
                    "compile_time_s": round(
                        m_b["compile_time_s"] - m_a["compile_time_s"], 4
                    ),
                }
                # per-request ledger of the leg: the warm wave's
                # reused_blocks tell exactly how much prefill the cache
                # skipped — a sub-1.0 "speedup" with full reuse points
                # at dispatch/compile overhead, with zero reuse at
                # eviction (or a probe too short to span a page)
                probe_summary[leg] = {
                    **ledger_agg(summaries[i0:i1]),
                    "ttft_p50_s": round(ttft, 4),
                    "wall_s": round(wall, 4),
                }
                i0 = i1
            speedup = legs["cold"]["ttft"] / legs["warm"]["ttft"]
            prefix_ab["ttft_speedup"] = round(speedup, 3)
            goodput["prefix_probe"] = {
                **probe_summary, "ttft_speedup": round(speedup, 3),
            }
            return legs, prefix_ab

        # ---- host-tier offload probe (BASELINE.md's +40% TTFT claim),
        # also shared by FAST and full runs: serve a fresh prompt, wait
        # for its pages to write-through to the host pool, EVICT them
        # from HBM, re-serve — restore-from-host vs full recompute,
        # under the cost gate. `restored > 0` here is the standing proof
        # the tier works (the r06 gate sat idle because the FAST probe
        # never forced an eviction).
        async def offload_probe_run():
            from dynamo_tpu.llm.tokens import compute_block_hashes

            def evict_all():
                grabbed = []
                while True:
                    got = engine.allocator.allocate(1)
                    if not got:
                        break
                    grabbed.extend(got)
                engine.allocator.release(grabbed)

            async def await_offloaded(tokens):
                hs = compute_block_hashes(tokens, engine.page_size)
                hs = hs[: PROBE_ISL // engine.page_size]
                for _ in range(200):
                    if engine.host_pool is not None and all(
                        h in engine.host_pool for h in hs
                    ):
                        return True
                    engine._wake.set()
                    await asyncio.sleep(0.05)
                return False

            engine.offload_paused = False
            # warm cycle: the restore path (H2D inject + registration)
            # has its own compile families — pay them before measuring
            wprobe = rng.randint(1, cfg.vocab_size, size=PROBE_ISL).tolist()
            await one(wprobe, {})
            if await await_offloaded(wprobe):
                evict_all()
                await one(wprobe, {})

            oprobe = rng.randint(1, cfg.vocab_size, size=PROBE_ISL).tolist()
            ocold, owarm = {}, {}
            await one(oprobe, ocold)
            offloaded = await await_offloaded(oprobe)
            # evict every evictable HBM page (incl. the probe's)
            evict_all()
            i_ow = len(summaries)
            await one(oprobe, owarm)
            engine.offload_paused = True
            speedup = _probe_ratio(ocold, owarm) if offloaded else None
            # the re-serve's ledger says whether the tier RESTORED or
            # the gate declined (and why) — the "restored: 0, declined:
            # 0" blindness of BENCH_r06 becomes an attributed decision
            goodput["offload_probe"] = {
                "offloaded": bool(offloaded),
                "warm": ledger_agg(summaries[i_ow:]),
                "ttft_speedup": round(speedup, 3) if speedup else None,
            }
            return speedup

        if FAST:
            legs, prefix_ab = await prefix_probe(min(4, concurrency))
            offload_speedup = await offload_probe_run()
            return (
                records, wall, wall_spread, phase_delta,
                None, None,
                {
                    "ttft": legs["cold"]["ttft"] / legs["warm"]["ttft"],
                    "wall": legs["cold"]["wall"] / legs["warm"]["wall"],
                },
                [], 0.0, 0.0, [], 0.0, 0.0, offload_speedup,
                await spec_ab() if SPEC else None,
                await mixed_ab() if MIXED else None,
                await mixed_spec_ab() if (SPEC and MIXED) else None,
                await pipeline_ab() if PIPE else None,
                prefix_ab,
            )

        legs, prefix_ab = await prefix_probe(min(32, concurrency))
        cold = {"ttft": legs["cold"]["ttft"]}
        warm = {"ttft": legs["warm"]["ttft"]}
        prefix_cold_wall = legs["cold"]["wall"]
        prefix_warm_wall = legs["warm"]["wall"]

        offload_speedup = await offload_probe_run()

        # ---- paced (Poisson) arrivals: the reference benches with
        # genai-perf's paced load (perf.sh:22-46); closed-loop-burst TTFT
        # (every request arriving at t=0) says nothing about latency at a
        # given request RATE. Pace at BENCH_PACED_FRAC of the closed-loop
        # request rate and report p50/p95 TTFT there.
        closed_rate = concurrency / wall  # requests/s sustained

        async def paced_run(frac):
            rate = frac * closed_rate
            n_paced = concurrency
            recs = [dict() for _ in range(n_paced)]
            gaps = rng.exponential(1.0 / rate, size=n_paced)
            tasks = []
            tp0 = time.perf_counter()
            for i in range(n_paced):
                p = rng.randint(1, cfg.vocab_size, size=ISL).tolist()
                tasks.append(asyncio.create_task(one(p, recs[i])))
                await asyncio.sleep(float(gaps[i]))
            await asyncio.gather(*tasks)
            return rate, recs, time.perf_counter() - tp0

        # two operating points: below the knee (TTFT ~ service latency)
        # and at ~50% of closed-loop (the prefill plane saturates when
        # arrivals come singly — TTFT is queue-dominated there)
        lo_frac = float(os.environ.get("BENCH_PACED_FRAC", "0.35"))
        hi_frac = float(os.environ.get("BENCH_PACED_FRAC_HI", "0.5"))
        paced_rate, paced_records, paced_wall = await paced_run(lo_frac)
        hi_rate, hi_records, hi_wall = await paced_run(hi_frac)

        return (
            records, wall, wall_spread, phase_delta,
            prefill_wall, prefill_wave_tokens,
            {
                "ttft": _probe_ratio(cold, warm),
                "wall": prefix_cold_wall / prefix_warm_wall,
            },
            paced_records, paced_rate, paced_wall,
            hi_records, hi_rate, hi_wall,
            offload_speedup,
            await spec_ab() if SPEC else None,
            await mixed_ab() if MIXED else None,
            await mixed_spec_ab() if (SPEC and MIXED) else None,
            await pipeline_ab() if PIPE else None,
            prefix_ab,
        )

    (
        records, wall, wall_spread, phase_delta,
        prefill_wall, prefill_wave_tokens,
        prefix_speedup,
        paced_records, paced_rate, paced_wall,
        hi_records, hi_rate, hi_wall,
        offload_speedup,
        spec_result,
        mixed_result,
        mixed_spec_result,
        pipeline_result,
        prefix_ab_result,
    ) = asyncio.run(run())
    total_tokens = sum(r["tokens"] for r in records)
    toks_per_sec_chip = total_tokens / wall / n_chips
    ttft_p50 = float(np.percentile([r["ttft"] for r in records], 50))
    itls = [r["itl"] for r in records if r["itl"] is not None]
    itl_p50 = float(np.percentile(itls, 50)) if itls else 0.0

    # SLO goodput over the measured wave: a request's tokens count only
    # when its client TTFT met the budget (exactly-at attains) — the
    # number the SLO-driven planner should defend, as opposed to raw
    # throughput which can look healthy while every request breaches
    good = [r for r in records if r["ttft"] <= SLO_TTFT]
    goodput["slo"] = {
        "ttft_target_s": SLO_TTFT,
        "attained_frac": round(len(good) / len(records), 4),
        "goodput_toks_per_sec_chip": round(
            sum(r["tokens"] for r in good) / wall / n_chips, 2
        ),
        "throughput_toks_per_sec_chip": round(toks_per_sec_chip, 2),
    }
    goodput["offload_gate"] = dict(engine.offload_gate_stats)

    def p50(recs, key):
        vals = [r[key] for r in recs if r.get(key) is not None]
        return round(float(np.percentile(vals, 50)), 4) if vals else None

    # phase split: measured prefill-only wave (engine-confirmed token
    # count) + combined wall minus it for the decode share — the
    # dispatch-call counters go into the artifact raw for transparency
    prefill_rate = decode_rate = None
    if prefill_wall and prefill_wave_tokens:
        prefill_rate = prefill_wave_tokens / prefill_wall / n_chips
        decode_wall = wall - prefill_wall
        if decode_wall > wall * 0.05:
            decode_rate = total_tokens / decode_wall / n_chips

    if big:
        # the real north-star model: vs_baseline is the UNSCALED 2000
        # tok/s/GPU bar (BASELINE.json), no parameter-count modeling
        target = PARITY_8B_TOKS_PER_CHIP
    else:
        target = PARITY_8B_TOKS_PER_CHIP * (_8B_PARAMS / n_params)
    headline_note = None
    if n_params < 5e8:
        # the r06 trap: a tiny/debug preset makes vs_baseline read ~0.0
        # and goes DARK on the real-model trajectory (r03: 5247, r04:
        # 1339 tok/s/chip at llama scale). Tiny-scale coverage belongs
        # to BENCH_SCENARIOS (its engines are independent of the
        # headline model) — the headline itself should stay real.
        headline_note = (
            f"headline model '{cfg.name}' ({n_params:.0f} params) is NOT "
            "the real-model trajectory; vs_baseline vs the parameter-"
            "scaled 8B bar is not comparable to the r03/r04 llama "
            "numbers. Unset BENCH_MODEL (auto-picks the largest llama "
            "preset for the chip) to re-measure the real headline; use "
            "BENCH_SCENARIOS=1 for tiny-scale workload coverage."
        )
        import sys as _sys

        print(f"bench: {headline_note}", file=_sys.stderr)
    qtag = f" {QUANT}" if QUANT else ""
    qtag += f" {KV_QUANT}kv" if KV_QUANT else ""
    headline = {
                "metric": f"{cfg.name}{qtag} serving "
                f"decode throughput (ISL={ISL} OSL={OSL} conc={concurrency})",
                "value": round(toks_per_sec_chip, 2),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(toks_per_sec_chip / target, 4),
                "extra": {
                    "model": cfg.name,
                    # non-None exactly when the benched model cannot
                    # speak for the real-model trajectory (BENCH_NOTES.md)
                    **({} if headline_note is None else {
                        "headline_note": headline_note,
                    }),
                    "p50_ttft_s": round(ttft_p50, 4),
                    # engine-side split (scheduler stamps): p50 of
                    # submit->prefill-dispatch-returned, and the slot
                    # queue wait — client TTFT minus engine TTFT is the
                    # tunnel fetch/delivery share
                    "engine_p50_ttft_s": p50(records, "engine_ttft"),
                    "engine_p50_queue_wait_s": p50(records, "queue_wait"),
                    "p50_itl_s": round(itl_p50, 6),
                    "chips": n_chips,
                    "params": n_params,
                    "parity_target_toks_per_chip": round(target, 1),
                    # median-of-N wave walls (tunnel drift record)
                    "bench_reps": len(wall_spread),
                    "wave_walls_s": wall_spread,
                    # the wall includes prefilling ISL tokens per request;
                    # total token throughput shows the full device output
                    "total_toks_per_sec_chip": round(
                        (concurrency * ISL + total_tokens) / wall / n_chips, 1
                    ),
                    # MEASURED phases: prefill from a dedicated OSL=1
                    # wave (engine-counter-confirmed tokens), decode from
                    # the combined wall minus it
                    "prefill_phase_toks_per_sec_chip": (
                        round(prefill_rate, 1) if prefill_rate else None
                    ),
                    "decode_phase_toks_per_sec_chip": (
                        round(decode_rate, 1) if decode_rate else None
                    ),
                    # raw engine counters over the measured waves
                    # (dispatch-CALL walls — async through the tunnel,
                    # NOT device walls; prefill tokens exact, decode
                    # tokens = dispatched slots incl. overshoot)
                    "engine_phase_counters": {
                        k: round(v, 3) if isinstance(v, float) else v
                        for k, v in phase_delta.items()
                    },
                    # Poisson arrivals at two operating points: below
                    # the knee (default 0.35x closed-loop) and at the
                    # queue-dominated 0.5x point
                    **({} if not paced_records else {
                        "paced_rate_req_s": round(paced_rate, 2),
                        "paced_p50_ttft_s": p50(paced_records, "ttft"),
                        "paced_p95_ttft_s": round(float(np.percentile(
                            [r["ttft"] for r in paced_records], 95)), 4),
                        "paced_engine_p50_ttft_s": p50(
                            paced_records, "engine_ttft"
                        ),
                        "paced_engine_p50_queue_wait_s": p50(
                            paced_records, "queue_wait"
                        ),
                        "paced_toks_per_sec_chip": round(
                            sum(r["tokens"] for r in paced_records)
                            / paced_wall / n_chips, 1
                        ),
                        "paced_hi_rate_req_s": round(hi_rate, 2),
                        "paced_hi_p50_ttft_s": p50(hi_records, "ttft"),
                        "paced_hi_p95_ttft_s": round(float(np.percentile(
                            [r["ttft"] for r in hi_records], 95)), 4),
                        "paced_hi_engine_p50_ttft_s": p50(
                            hi_records, "engine_ttft"
                        ),
                    }),
                    # wave-based cold/warm p50 TTFT + wall on identical
                    # prompt sets (prefix cache under real queuing)
                    # SLO-gated goodput (BENCH_SLO_TTFT budget): tokens
                    # from requests whose TTFT met the target
                    "slo_goodput": goodput.get("slo"),
                    "prefix_hit_ttft_speedup": round(prefix_speedup["ttft"], 2),
                    "prefix_hit_wall_speedup": (
                        round(prefix_speedup["wall"], 2)
                        if prefix_speedup["wall"] else None
                    ),
                    # restore-from-host-tier TTFT vs full recompute
                    # (HBM pages evicted between serves). The engine's
                    # cost gate declines restores that would LOSE to
                    # recompute (calibrated from measured rates), so on
                    # rigs where H2D is slow this probe converges to
                    # ~1.0 instead of below it
                    "offload_hit_ttft_speedup": (
                        round(offload_speedup, 2)
                        if offload_speedup is not None else None
                    ),
                    "offload_gate": dict(engine.offload_gate_stats),
                    # BENCH_SPEC=1: repetitive-text A/B, spec off vs on
                    **({} if spec_result is None else {
                        "spec": spec_result,
                    }),
                    # BENCH_MIXED=1: admission-wave A/B, mixed batching
                    # off vs on (held-decode ITL during the wave)
                    **({} if mixed_result is None else {
                        "mixed": mixed_result,
                    }),
                    # BENCH_MIXED=1 BENCH_SPEC=1: composed spec x mixed
                    # A/B (ragged verify rows riding the mixed steps)
                    **({} if mixed_spec_result is None else {
                        "mixed_spec": mixed_spec_result,
                    }),
                    # BENCH_PIPELINE=1 (or BENCH_MIXED=1): step-pipeline
                    # A/B — sync-fetch wall fraction, serialized vs
                    # pipelined
                    **({} if pipeline_result is None else {
                        "pipeline_ab": pipeline_result,
                    }),
                },
            }
    # fleet scenarios LAST (they spawn their own hub + workers; the
    # engine above is done by now, so nothing contends)
    if PREFIX_FLEET or CONTROL or FAILOVER or KV_CAPACITY:
        import sys as _sys

        _sys.path.insert(
            0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "scripts")
        )
    prefix_fleet_result = None
    if PREFIX_FLEET:
        import prefix_fleet

        prefix_fleet_result = prefix_fleet.run()
        print(
            "prefix_fleet: warm_vs_cold={} route_to_holder={} pulls={} "
            "usd_per_mtok={}".format(
                prefix_fleet_result["warm_vs_cold_ttft"],
                prefix_fleet_result["route_to_holder_frac"],
                prefix_fleet_result["pulls"]["landed"],
                prefix_fleet_result["dollars"]["usd_per_mtok"],
            ),
            file=_sys.stderr,
        )
    scenarios_result = None
    if SCENARIOS:
        import gc
        import sys as _sys

        # scenario engines are tiny-by-default and independent of the
        # headline engine above, so the real-model headline and the
        # CI-scale scenario suite ride ONE invocation. The headline
        # engine's auto-sized KV pool holds most of free HBM though —
        # every stat it feeds the sections above is already
        # snapshotted, so close it and DROP the reference before any
        # scenario engine allocates (on a real chip the scenarios
        # would otherwise fight for the ~15% slack, or fail outright
        # at LOADGEN_SCALE=real).
        asyncio.run(engine.close())
        engine = None
        gc.collect()
        from dynamo_tpu.loadgen import bench as loadgen_bench

        scenarios_result = loadgen_bench.run_suite()
        n_ok = sum(
            1 for r in scenarios_result["results"].values()
            if "error" not in r
        )
        print(
            f"scenarios: {n_ok}/{len(scenarios_result['results'])} ok "
            f"(scale={scenarios_result['scale']['name']})",
            file=_sys.stderr,
        )
    failover_result = None
    if FAILOVER:
        import failover_chaos

        failover_result = failover_chaos.run()
        print(
            "failover: recovered_frac={} byte_identical={} gap_p50={}s "
            "tokens={}".format(
                failover_result["recovered_frac"],
                failover_result["byte_identical"],
                failover_result["replay_ttft_gap_p50_s"],
                failover_result["tokens"],
            ),
            file=_sys.stderr,
        )
    control_result = None
    if CONTROL:
        import control_chaos

        control_result = control_chaos.run()
        # the sampler timeline is diagnostic; cap it so BENCH_OUT stays
        # a small trajectory artifact
        control_result["timeline"] = control_result["timeline"][:200]
        print(
            "control: ttr={} goodput_retained={} ups={} drain_clean={}".format(
                control_result["time_to_recover_s"],
                control_result["goodput"]["retained"],
                control_result["scaling"]["ups"],
                control_result["drain"]["clean"],
            ),
            file=_sys.stderr,
        )
    tp_overlap_result = None
    if TP_OVERLAP:
        import subprocess
        import sys as _sys

        # subprocess: the section needs a fresh jax on 8 virtual CPU
        # devices, and this process is already bound to the real backend
        proc = subprocess.run(
            [
                _sys.executable,
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "scripts", "tp_overlap_bench.py",
                ),
            ],
            capture_output=True, text=True, timeout=600,
        )
        if proc.returncode:
            raise RuntimeError(
                "tp_overlap bench failed (rc=%d):\n%s"
                % (proc.returncode, proc.stderr[-4000:])
            )
        tp_overlap_result = json.loads(proc.stdout.splitlines()[-1])
        print(
            "tp_overlap: exposed_ratio={} wall serialized={}s "
            "overlap={}s identical={}".format(
                tp_overlap_result["exposed_ratio"],
                tp_overlap_result["legs"]["serialized"]["layer_step_wall_s"],
                tp_overlap_result["legs"]["overlap"]["layer_step_wall_s"],
                tp_overlap_result["greedy_byte_identical_vs_tp1"],
            ),
            file=_sys.stderr,
        )
    kv_capacity_result = None
    if KV_CAPACITY:
        import kv_capacity

        kv_capacity_result = kv_capacity.run(budget_mb=KV_CAPACITY_MB)
        cap = kv_capacity_result["capacity"]
        print(
            "kv_capacity: streams bf16={} int8={} int4={} "
            "(x{} vs bf16) int4_match={}".format(
                cap["tiers"]["bf16"]["resident_streams"],
                cap["tiers"]["int8"]["resident_streams"],
                cap["tiers"]["int4"]["resident_streams"],
                cap["capacity_ratio_int4_vs_bf16"],
                kv_capacity_result["quality"]["tiers"]["int4"][
                    "greedy_token_match"
                ],
            ),
            file=_sys.stderr,
        )

    print(json.dumps(headline))
    if BENCH_OUT:
        # machine-readable trajectory artifact: one file, every section
        # keyed (null = section not requested this run)
        sections = {
                    "headline": headline,
                    "spec": spec_result,
                    "mixed": mixed_result,
                    "mixed_spec": mixed_spec_result,
                    "pipeline_ab": pipeline_result,
                    # prefix probe attribution (always present): per-leg
                    # prefill/prefix/compile counter deltas of the
                    # cold/warm waves — the breakdown that explains the
                    # headline prefix_hit_ttft_speedup
                    "prefix_ab": prefix_ab_result,
                    # BENCH_PREFIX_FLEET=1: multi-tenant shared-prefix
                    # fleet scenario (two workers + KV router + pulls)
                    "prefix_fleet": prefix_fleet_result,
                    # BENCH_CONTROL=1: chaos-controller recovery curve
                    # (worker death + spike vs the SLO-driven planner)
                    "control": control_result,
                    # BENCH_FAILOVER=1: request-failover chaos proof
                    # (worker.die mid-stream -> byte-identical resume;
                    # recovered_frac + replay gap + token economics)
                    "failover": failover_result,
                    # BENCH_KV_CAPACITY=1: KV-tier capacity census —
                    # per-tier page bytes + resident streams at a
                    # fixed byte budget, per-tier decode waves, and
                    # the margin-stable greedy token-match quality
                    # bound vs the f32-KV reference
                    "kv_capacity": kv_capacity_result,
                    # BENCH_TP_OVERLAP=1: TP comm/compute overlap ledger
                    # — serialized vs overlapped per-layer step wall +
                    # the measured collective-byte ledger (exposed
                    # exactly 0.5x, total conserved) + greedy
                    # byte-identity vs tp=1
                    "tp_overlap": tp_overlap_result,
                    # BENCH_SCENARIOS=1: the trace-driven scenario suite
                    # (dynamo_tpu/loadgen/) — {scale, results: {name:
                    # section}}, each section scored by SLO-gated
                    # goodput with its trace identity (docs/loadgen.md)
                    "scenarios": scenarios_result,
                    # goodput accounting (always present): SLO-gated
                    # throughput over the measured wave + the
                    # per-request prefix/offload ledgers of the probes
                    "goodput": goodput,
        }
        # provenance: extra.rev (git SHA) + extra.ts in EVERY section,
        # so scripts/bench_history.py joins runs to commits without
        # filename archaeology
        _stamp_provenance(sections)
        with open(BENCH_OUT, "w") as f:
            json.dump(sections, f, indent=2)
            f.write("\n")
    if BENCH_TRACE:
        import sys

        from dynamo_tpu.utils import tracing as _tracing

        # stdout stays the one-line headline artifact; the trace note
        # goes to stderr like other diagnostics. The ring is process-
        # global (the engine may already be closed when BENCH_SCENARIOS
        # freed its HBM above), so dump via the tracing module.
        n_ev = _tracing.dump(BENCH_TRACE)
        print(f"trace: {n_ev} events -> {BENCH_TRACE}", file=sys.stderr)


def _stamp_provenance(sections: dict) -> None:
    """extra.rev (git SHA) + extra.ts on every emitted section: the
    join key scripts/bench_history.py uses to line a BENCH_OUT up
    against commits. GITHUB_SHA wins (CI checkouts can be detached or
    shallow); a local git rev-parse covers dev runs; rev stays null
    outside both."""
    import subprocess

    rev = os.environ.get("GITHUB_SHA") or None
    if not rev:
        try:
            rev = subprocess.run(
                ["git", "rev-parse", "HEAD"],
                capture_output=True, text=True, timeout=10,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip() or None
        except Exception:  # noqa: BLE001 — provenance is best-effort
            rev = None
    ts = int(time.time())
    for section in sections.values():
        if isinstance(section, dict):
            extra = section.setdefault("extra", {})
            extra.setdefault("rev", rev)
            extra.setdefault("ts", ts)


if __name__ == "__main__":
    import sys

    if any(a in ("-h", "--help") for a in sys.argv[1:]):
        print(ENV_HELP, end="")
    else:
        main()
