// libdynamo_kv_events: C-ABI KV-event publisher for engine integration.
//
// Native equivalent of the reference's C bindings
// (reference: lib/bindings/c/src/lib.rs:52-297 — dynamo_llm_init /
// dynamo_kv_event_publish_stored / _removed, loaded via ctypes by the
// vLLM patch's event_manager.py). An external engine links (or dlopens)
// this library and reports its prefix-cache block lifecycle; events land
// on the hub subject "{ns}.{component}.kv_events" as msgpack RouterEvents
// (dynamo_tpu/llm/kv_router/protocols.py), exactly what KvIndexer
// subscribers consume.
//
// Deviation from the reference FFI: the reference's Rust lib hashes raw
// token ids internally; here chained xxh3 hashing lives in the engine
// layer (dynamo_tpu/llm/tokens.py), so the C API carries the computed
// block/tokens hashes. Publishes are fire-and-forget frames (no "i"
// request id -> the hub sends no reply), matching the event plane's
// at-most-once semantics.
//
// Thread-safe: one internal mutex serializes socket writes.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <netdb.h>
#include <pthread.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <string>

#include "msgpack.hpp"

using msgpack::Value;

namespace {

struct State {
  int fd = -1;
  std::string subject;
  long long worker_id = 0;
  int block_size = 0;
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
};

State g_state;

int send_frame(const Value& v) {
  std::string buf = msgpack::frame_encode(v);
  size_t off = 0;
  while (off < buf.size()) {
    ssize_t w = ::send(g_state.fd, buf.data() + off, buf.size() - off, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    off += static_cast<size_t>(w);
  }
  return 0;
}

int publish_event(Value event) {
  // the whole build+send runs under the mutex: init() may rebind
  // subject/worker_id/fd concurrently from another thread
  pthread_mutex_lock(&g_state.mu);
  event.set("block_size", Value::integer(g_state.block_size));
  Value router = Value::mapv();
  router.set("worker_id", Value::integer(g_state.worker_id));
  router.set("event", std::move(event));

  Value frame = Value::mapv();  // no "i": fire-and-forget, hub sends no reply
  frame.set("op", Value::str("publish"));
  frame.set("subject", Value::str(g_state.subject));
  frame.set("data", Value::bin(msgpack::pack(router)));

  int rc = g_state.fd >= 0 ? send_frame(frame) : -1;
  pthread_mutex_unlock(&g_state.mu);
  return rc;
}

}  // namespace

extern "C" {

// Connect to the hub and bind the publisher identity. Returns 0 on
// success, negative errno-style codes on failure.
int dyn_llm_init(const char* host, int port, const char* ns,
                 const char* component, long long worker_id,
                 int kv_block_size) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    // hostname (k8s service / localhost): resolve like HubClient does
    struct addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    if (getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr) {
      close(fd);
      return -2;
    }
    addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  }
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    close(fd);
    return -3;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  pthread_mutex_lock(&g_state.mu);
  if (g_state.fd >= 0) close(g_state.fd);
  g_state.fd = fd;
  g_state.subject = std::string(ns) + "." + component + ".kv_events";
  g_state.worker_id = worker_id;
  g_state.block_size = kv_block_size;
  pthread_mutex_unlock(&g_state.mu);
  return 0;
}

// Publish a "stored" event: num_blocks parent-chained blocks entering the
// worker's prefix cache. parent_hash is ignored when has_parent == 0
// (a root block). page_ids may be NULL.
int dyn_kv_event_publish_stored(unsigned long long event_id,
                                unsigned long long parent_hash, int has_parent,
                                const unsigned long long* block_hashes,
                                const unsigned long long* tokens_hashes,
                                const int* page_ids, int num_blocks) {
  if (num_blocks < 0 || !block_hashes || !tokens_hashes) return -4;
  Value blocks = Value::array();
  for (int k = 0; k < num_blocks; ++k) {
    Value b = Value::mapv();
    b.set("block_hash", Value::uinteger(block_hashes[k]));
    b.set("tokens_hash", Value::uinteger(tokens_hashes[k]));
    b.set("page_id", Value::integer(page_ids ? page_ids[k] : 0));
    blocks.arr.push_back(std::move(b));
  }
  Value ev = Value::mapv();
  ev.set("type", Value::str("stored"));
  ev.set("event_id", Value::uinteger(event_id));
  ev.set("parent_hash", has_parent ? Value::uinteger(parent_hash) : Value::nil());
  ev.set("blocks", std::move(blocks));
  ev.set("block_hashes", Value::array());
  ev.set("tier", Value::str("device"));
  return publish_event(std::move(ev));  // block_size added under the mutex
}

// Publish a "removed" event: blocks leaving the worker's prefix cache.
int dyn_kv_event_publish_removed(unsigned long long event_id,
                                 const unsigned long long* block_hashes,
                                 int num_blocks) {
  if (num_blocks < 0 || !block_hashes) return -4;
  Value hashes = Value::array();
  for (int k = 0; k < num_blocks; ++k)
    hashes.arr.push_back(Value::uinteger(block_hashes[k]));
  Value ev = Value::mapv();
  ev.set("type", Value::str("removed"));
  ev.set("event_id", Value::uinteger(event_id));
  ev.set("parent_hash", Value::nil());
  ev.set("blocks", Value::array());
  ev.set("block_hashes", std::move(hashes));
  ev.set("tier", Value::str("device"));
  return publish_event(std::move(ev));  // block_size added under the mutex
}

void dyn_llm_shutdown() {
  pthread_mutex_lock(&g_state.mu);
  if (g_state.fd >= 0) {
    close(g_state.fd);
    g_state.fd = -1;
  }
  pthread_mutex_unlock(&g_state.mu);
}

}  // extern "C"
