// Minimal msgpack value + codec for the dynamo-tpu native runtime.
//
// Covers exactly the wire subset msgpack-python (use_bin_type=True,
// raw=False) produces for the hub protocol (dynamo_tpu/runtime/hub/codec.py):
// nil, bool, int/uint (all widths), float32/64, str, bin, array, map.
// Faithful int-vs-uint roundtrip matters because 64-bit block hashes can
// exceed int64. Header-only; no external dependencies.

#pragma once

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace msgpack {

struct Value;
using Map = std::vector<std::pair<Value, Value>>;
using Array = std::vector<Value>;

struct Value {
  enum class Type : uint8_t { Nil, Bool, Int, Uint, Float, Str, Bin, Arr, MapT };
  Type type = Type::Nil;
  bool b = false;
  int64_t i = 0;
  uint64_t u = 0;
  double d = 0.0;
  std::string s;  // str and bin payloads
  Array arr;
  Map map;

  Value() = default;
  static Value nil() { return Value(); }
  static Value boolean(bool v) { Value x; x.type = Type::Bool; x.b = v; return x; }
  static Value integer(int64_t v) { Value x; x.type = Type::Int; x.i = v; return x; }
  static Value uinteger(uint64_t v) { Value x; x.type = Type::Uint; x.u = v; return x; }
  static Value real(double v) { Value x; x.type = Type::Float; x.d = v; return x; }
  static Value str(std::string v) { Value x; x.type = Type::Str; x.s = std::move(v); return x; }
  static Value bin(std::string v) { Value x; x.type = Type::Bin; x.s = std::move(v); return x; }
  static Value array(Array v = {}) { Value x; x.type = Type::Arr; x.arr = std::move(v); return x; }
  static Value mapv(Map v = {}) { Value x; x.type = Type::MapT; x.map = std::move(v); return x; }

  bool is_nil() const { return type == Type::Nil; }
  bool is_str() const { return type == Type::Str; }
  bool is_bin() const { return type == Type::Bin; }
  bool is_int() const { return type == Type::Int || type == Type::Uint; }
  bool is_map() const { return type == Type::MapT; }

  int64_t as_int() const {
    if (type == Type::Int) return i;
    if (type == Type::Uint) return static_cast<int64_t>(u);
    if (type == Type::Float) return static_cast<int64_t>(d);
    throw std::runtime_error("msgpack: not an int");
  }
  double as_double() const {
    if (type == Type::Float) return d;
    if (type == Type::Int) return static_cast<double>(i);
    if (type == Type::Uint) return static_cast<double>(u);
    throw std::runtime_error("msgpack: not a number");
  }
  const std::string& as_str() const {
    if (type != Type::Str) throw std::runtime_error("msgpack: not a str");
    return s;
  }
  const std::string& as_bytes() const {  // str or bin
    if (type != Type::Str && type != Type::Bin)
      throw std::runtime_error("msgpack: not bytes");
    return s;
  }
  bool truthy() const {
    switch (type) {
      case Type::Nil: return false;
      case Type::Bool: return b;
      case Type::Int: return i != 0;
      case Type::Uint: return u != 0;
      case Type::Float: return d != 0.0;
      default: return true;
    }
  }

  // map access; returns nil for missing keys (mirrors dict.get)
  const Value& get(const std::string& key) const {
    static const Value knil;
    if (type != Type::MapT) return knil;
    for (const auto& kv : map)
      if (kv.first.type == Type::Str && kv.first.s == key) return kv.second;
    return knil;
  }
  void set(const std::string& key, Value v) {
    if (type != Type::MapT) { type = Type::MapT; }
    for (auto& kv : map)
      if (kv.first.type == Type::Str && kv.first.s == key) {
        kv.second = std::move(v);
        return;
      }
    map.emplace_back(Value::str(key), std::move(v));
  }
};

// ------------------------------------------------------------------ encoding

inline void pack_into(std::string& out, const Value& v);

inline void put_be(std::string& out, uint64_t x, int nbytes) {
  for (int k = nbytes - 1; k >= 0; --k)
    out.push_back(static_cast<char>((x >> (8 * k)) & 0xff));
}

inline void pack_uint(std::string& out, uint64_t x) {
  if (x < 0x80) {
    out.push_back(static_cast<char>(x));
  } else if (x <= 0xff) {
    out.push_back(static_cast<char>(0xcc)); put_be(out, x, 1);
  } else if (x <= 0xffff) {
    out.push_back(static_cast<char>(0xcd)); put_be(out, x, 2);
  } else if (x <= 0xffffffffULL) {
    out.push_back(static_cast<char>(0xce)); put_be(out, x, 4);
  } else {
    out.push_back(static_cast<char>(0xcf)); put_be(out, x, 8);
  }
}

inline void pack_int(std::string& out, int64_t x) {
  if (x >= 0) { pack_uint(out, static_cast<uint64_t>(x)); return; }
  if (x >= -32) {
    out.push_back(static_cast<char>(x));
  } else if (x >= INT8_MIN) {
    out.push_back(static_cast<char>(0xd0)); put_be(out, static_cast<uint8_t>(x), 1);
  } else if (x >= INT16_MIN) {
    out.push_back(static_cast<char>(0xd1)); put_be(out, static_cast<uint16_t>(x), 2);
  } else if (x >= INT32_MIN) {
    out.push_back(static_cast<char>(0xd2)); put_be(out, static_cast<uint32_t>(x), 4);
  } else {
    out.push_back(static_cast<char>(0xd3)); put_be(out, static_cast<uint64_t>(x), 8);
  }
}

inline void pack_into(std::string& out, const Value& v) {
  using T = Value::Type;
  switch (v.type) {
    case T::Nil: out.push_back(static_cast<char>(0xc0)); break;
    case T::Bool: out.push_back(static_cast<char>(v.b ? 0xc3 : 0xc2)); break;
    case T::Int: pack_int(out, v.i); break;
    case T::Uint: pack_uint(out, v.u); break;
    case T::Float: {
      out.push_back(static_cast<char>(0xcb));
      uint64_t bits;
      static_assert(sizeof(bits) == sizeof(v.d), "double width");
      std::memcpy(&bits, &v.d, 8);
      put_be(out, bits, 8);
      break;
    }
    case T::Str: {
      size_t n = v.s.size();
      if (n < 32) out.push_back(static_cast<char>(0xa0 | n));
      else if (n <= 0xff) { out.push_back(static_cast<char>(0xd9)); put_be(out, n, 1); }
      else if (n <= 0xffff) { out.push_back(static_cast<char>(0xda)); put_be(out, n, 2); }
      else { out.push_back(static_cast<char>(0xdb)); put_be(out, n, 4); }
      out.append(v.s);
      break;
    }
    case T::Bin: {
      size_t n = v.s.size();
      if (n <= 0xff) { out.push_back(static_cast<char>(0xc4)); put_be(out, n, 1); }
      else if (n <= 0xffff) { out.push_back(static_cast<char>(0xc5)); put_be(out, n, 2); }
      else { out.push_back(static_cast<char>(0xc6)); put_be(out, n, 4); }
      out.append(v.s);
      break;
    }
    case T::Arr: {
      size_t n = v.arr.size();
      if (n < 16) out.push_back(static_cast<char>(0x90 | n));
      else if (n <= 0xffff) { out.push_back(static_cast<char>(0xdc)); put_be(out, n, 2); }
      else { out.push_back(static_cast<char>(0xdd)); put_be(out, n, 4); }
      for (const auto& e : v.arr) pack_into(out, e);
      break;
    }
    case T::MapT: {
      size_t n = v.map.size();
      if (n < 16) out.push_back(static_cast<char>(0x80 | n));
      else if (n <= 0xffff) { out.push_back(static_cast<char>(0xde)); put_be(out, n, 2); }
      else { out.push_back(static_cast<char>(0xdf)); put_be(out, n, 4); }
      for (const auto& kv : v.map) {
        pack_into(out, kv.first);
        pack_into(out, kv.second);
      }
      break;
    }
  }
}

inline std::string pack(const Value& v) {
  std::string out;
  pack_into(out, v);
  return out;
}

// One frame as sent on the wire: 4-byte big-endian length + msgpack body
// (matches dynamo_tpu/runtime/hub/codec.py).
inline std::string frame_encode(const Value& v) {
  std::string payload = pack(v);
  std::string out;
  out.reserve(payload.size() + 4);
  put_be(out, payload.size(), 4);
  out.append(payload);
  return out;
}

// ------------------------------------------------------------------ decoding

struct Reader {
  const uint8_t* p;
  size_t n;
  size_t off = 0;

  uint8_t byte() {
    if (off >= n) throw std::runtime_error("msgpack: truncated");
    return p[off++];
  }
  uint64_t be(int nbytes) {
    if (off + nbytes > n) throw std::runtime_error("msgpack: truncated");
    uint64_t x = 0;
    for (int k = 0; k < nbytes; ++k) x = (x << 8) | p[off + k];
    off += nbytes;
    return x;
  }
  std::string bytes(size_t len) {
    if (off + len > n) throw std::runtime_error("msgpack: truncated");
    std::string s(reinterpret_cast<const char*>(p + off), len);
    off += len;
    return s;
  }
};

inline Value unpack_one(Reader& r, int depth = 0) {
  if (depth > 64) throw std::runtime_error("msgpack: nesting too deep");
  uint8_t c = r.byte();
  if (c < 0x80) return Value::integer(c);                        // pos fixint
  if (c >= 0xe0) return Value::integer(static_cast<int8_t>(c));  // neg fixint
  if ((c & 0xf0) == 0x80) {  // fixmap
    Value v = Value::mapv();
    size_t cnt = c & 0x0f;
    for (size_t k = 0; k < cnt; ++k) {
      Value key = unpack_one(r, depth + 1);
      v.map.emplace_back(std::move(key), unpack_one(r, depth + 1));
    }
    return v;
  }
  if ((c & 0xf0) == 0x90) {  // fixarray
    Value v = Value::array();
    size_t cnt = c & 0x0f;
    for (size_t k = 0; k < cnt; ++k) v.arr.push_back(unpack_one(r, depth + 1));
    return v;
  }
  if ((c & 0xe0) == 0xa0) return Value::str(r.bytes(c & 0x1f));  // fixstr
  switch (c) {
    case 0xc0: return Value::nil();
    case 0xc2: return Value::boolean(false);
    case 0xc3: return Value::boolean(true);
    case 0xc4: return Value::bin(r.bytes(r.be(1)));
    case 0xc5: return Value::bin(r.bytes(r.be(2)));
    case 0xc6: return Value::bin(r.bytes(r.be(4)));
    case 0xca: {
      uint32_t bits = static_cast<uint32_t>(r.be(4));
      float f;
      std::memcpy(&f, &bits, 4);
      return Value::real(f);
    }
    case 0xcb: {
      uint64_t bits = r.be(8);
      double d;
      std::memcpy(&d, &bits, 8);
      return Value::real(d);
    }
    case 0xcc: return Value::integer(static_cast<int64_t>(r.be(1)));
    case 0xcd: return Value::integer(static_cast<int64_t>(r.be(2)));
    case 0xce: return Value::integer(static_cast<int64_t>(r.be(4)));
    case 0xcf: {
      uint64_t x = r.be(8);
      if (x > static_cast<uint64_t>(INT64_MAX)) return Value::uinteger(x);
      return Value::integer(static_cast<int64_t>(x));
    }
    case 0xd0: return Value::integer(static_cast<int8_t>(r.be(1)));
    case 0xd1: return Value::integer(static_cast<int16_t>(r.be(2)));
    case 0xd2: return Value::integer(static_cast<int32_t>(r.be(4)));
    case 0xd3: return Value::integer(static_cast<int64_t>(r.be(8)));
    case 0xd9: return Value::str(r.bytes(r.be(1)));
    case 0xda: return Value::str(r.bytes(r.be(2)));
    case 0xdb: return Value::str(r.bytes(r.be(4)));
    case 0xdc: {
      Value v = Value::array();
      size_t cnt = r.be(2);
      for (size_t k = 0; k < cnt; ++k) v.arr.push_back(unpack_one(r, depth + 1));
      return v;
    }
    case 0xdd: {
      Value v = Value::array();
      size_t cnt = r.be(4);
      for (size_t k = 0; k < cnt; ++k) v.arr.push_back(unpack_one(r, depth + 1));
      return v;
    }
    case 0xde: case 0xdf: {
      Value v = Value::mapv();
      size_t cnt = r.be(c == 0xde ? 2 : 4);
      for (size_t k = 0; k < cnt; ++k) {
        Value key = unpack_one(r, depth + 1);
        v.map.emplace_back(std::move(key), unpack_one(r, depth + 1));
      }
      return v;
    }
    default:
      throw std::runtime_error("msgpack: unsupported tag " + std::to_string(c));
  }
}

inline Value unpack(const void* data, size_t len) {
  Reader r{static_cast<const uint8_t*>(data), len};
  Value v = unpack_one(r);
  if (r.off != r.n) throw std::runtime_error("msgpack: trailing bytes");
  return v;
}

}  // namespace msgpack
