// dynamo-hubd: native (C++) hub control plane for dynamo-tpu.
//
// Drop-in replacement for the Python hub server
// (dynamo_tpu/runtime/hub/server.py) speaking the identical
// length-prefixed-msgpack protocol (hub/codec.py), so every Python client
// (HubClient, DistributedRuntime, the C KV-event publisher) works
// unchanged. Semantics mirror the reference's etcd + NATS usage
// (reference: lib/runtime/src/transports/etcd.rs:41-540, nats.rs:50-214):
// lease-attached KV with prefix watches, wildcard pub/sub, competing-
// consumer queues, object-store buckets.
//
// Design: one poll(2) loop, one thread — every op is atomic with respect
// to every other, the same single-writer discipline as the asyncio hub
// and the reference's mailbox progress engines (SURVEY.md §5). Blocking
// q_pops and lease TTLs are poll-timeout-driven timers, not threads.
//
// Build: make -C native  (produces native/build/dynamo-hubd)
// Run:   dynamo-hubd [--host 127.0.0.1] [--port 0]
// Prints "LISTENING <port>" on stdout once bound (port 0 = ephemeral).

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

#include "msgpack.hpp"

using msgpack::Value;

static constexpr size_t kMaxFrame = 256u * 1024u * 1024u;  // codec.py cap
static constexpr double kLeaseTick = 0.25;                 // server.py LEASE_TICK_S

static double now_mono() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + static_cast<double>(ts.tv_nsec) * 1e-9;
}

struct KvEntry {
  std::string value;
  int64_t rev = 0;
  int64_t lease = 0;
};

struct Lease {
  double ttl = 10.0;
  double deadline = 0.0;
  std::set<std::string> keys;
};

struct PopWaiter {
  int conn_id = 0;
  int64_t msg_id = 0;
  bool has_deadline = false;
  double deadline = 0.0;
};

struct Conn {
  int fd = -1;
  int id = 0;
  std::string rbuf;
  size_t roff = 0;  // parse offset into rbuf
  std::string wbuf;
  size_t woff = 0;  // flush offset into wbuf
  std::set<int64_t> watches;
  std::set<int64_t> subs;
  bool dead = false;
};

class Hub {
 public:
  int listen_fd = -1;
  uint16_t port = 0;

  std::map<std::string, KvEntry> kv;
  int64_t revision = 0;
  std::unordered_map<int64_t, Lease> leases;
  int64_t next_lease_id = 0x1000;
  int next_conn_id = 1;
  std::unordered_map<int, std::unique_ptr<Conn>> conns;
  // (conn_id, client-chosen watch/sub id) -> prefix / subject pattern
  std::map<std::pair<int, int64_t>, std::string> watches;
  std::map<std::pair<int, int64_t>, std::string> subs;
  std::unordered_map<std::string, std::deque<Value>> queues;
  std::unordered_map<std::string, std::vector<PopWaiter>> pop_waiters;
  std::unordered_map<std::string, std::map<std::string, Value>> objects;
  double next_lease_sweep = 0.0;

  bool listen(const char* host, uint16_t want_port) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(want_port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      // hostname: resolve like the asyncio server does
      struct addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      struct addrinfo* res = nullptr;
      if (getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr)
        return false;
      addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    }
    if (bind(listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return false;
    if (::listen(listen_fd, 256) < 0) return false;
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    port = ntohs(addr.sin_port);
    set_nonblock(listen_fd);
    next_lease_sweep = now_mono() + kLeaseTick;
    return true;
  }

  static void set_nonblock(int fd) {
    fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
  }

  // ------------------------------------------------------------ out frames

  void send_value(Conn& c, const Value& v) {
    c.wbuf.append(msgpack::frame_encode(v));
  }

  void reply(Conn& c, const Value& req, Value result) {
    if (req.get("i").is_nil()) return;
    Value out = Value::mapv();
    out.set("i", req.get("i"));
    out.set("ok", Value::boolean(true));
    out.set("r", std::move(result));
    send_value(c, out);
  }

  void reply_err(Conn& c, const Value& req, const std::string& err) {
    if (req.get("i").is_nil()) return;
    Value out = Value::mapv();
    out.set("i", req.get("i"));
    out.set("ok", Value::boolean(false));
    out.set("e", Value::str(err));
    send_value(c, out);
  }

  void push_to(int conn_id, int64_t push_id, Value ev) {
    auto it = conns.find(conn_id);
    if (it == conns.end() || it->second->dead) return;
    Value out = Value::mapv();
    out.set("push", Value::integer(push_id));
    out.set("ev", std::move(ev));
    send_value(*it->second, out);
  }

  // ------------------------------------------------------------------- kv

  void notify_watchers(const char* type, const std::string& key,
                       const std::string* value, int64_t rev) {
    for (const auto& w : watches) {
      const std::string& prefix = w.second;
      if (key.compare(0, prefix.size(), prefix) == 0) {
        Value ev = Value::mapv();
        ev.set("type", Value::str(type));
        ev.set("key", Value::str(key));
        ev.set("value", value ? Value::bin(*value) : Value::nil());
        ev.set("rev", Value::integer(rev));
        push_to(w.first.first, w.first.second, std::move(ev));
      }
    }
  }

  int64_t kv_set(const std::string& key, const std::string& value,
                 int64_t lease_id) {
    if (lease_id) {
      auto it = leases.find(lease_id);
      if (it == leases.end())
        throw std::runtime_error("lease " + std::to_string(lease_id) + " not found");
      it->second.keys.insert(key);
    }
    auto old = kv.find(key);
    if (old != kv.end() && old->second.lease && old->second.lease != lease_id) {
      auto ol = leases.find(old->second.lease);
      if (ol != leases.end()) ol->second.keys.erase(key);
    }
    ++revision;
    kv[key] = KvEntry{value, revision, lease_id};
    notify_watchers("put", key, &value, revision);
    return revision;
  }

  bool kv_delete(const std::string& key) {
    auto it = kv.find(key);
    if (it == kv.end()) return false;
    if (it->second.lease) {
      auto ol = leases.find(it->second.lease);
      if (ol != leases.end()) ol->second.keys.erase(key);
    }
    kv.erase(it);
    ++revision;
    notify_watchers("delete", key, nullptr, revision);
    return true;
  }

  Value kv_entry_value(const std::string& key, const KvEntry& e) {
    Value v = Value::mapv();
    v.set("key", Value::str(key));
    v.set("value", Value::bin(e.value));
    v.set("rev", Value::integer(e.rev));
    v.set("lease", Value::integer(e.lease));
    return v;
  }

  Value kv_get_prefix(const std::string& prefix) {
    Value out = Value::array();
    // std::map is ordered: scan from lower_bound until prefix stops matching
    for (auto it = kv.lower_bound(prefix); it != kv.end(); ++it) {
      if (it->first.compare(0, prefix.size(), prefix) != 0) break;
      out.arr.push_back(kv_entry_value(it->first, it->second));
    }
    return out;
  }

  bool revoke_lease(int64_t lease_id) {
    auto it = leases.find(lease_id);
    if (it == leases.end()) return false;
    std::vector<std::string> keys(it->second.keys.begin(), it->second.keys.end());
    leases.erase(it);
    for (const auto& k : keys) kv_delete(k);
    return true;
  }

  // --------------------------------------------------------------- pub/sub

  static bool subject_matches(const std::string& pattern,
                              const std::string& subject) {
    if (pattern == subject) return true;
    if (pattern.size() >= 2 && pattern.compare(pattern.size() - 2, 2, ".>") == 0) {
      std::string head = pattern.substr(0, pattern.size() - 1);  // keep '.'
      if (subject.compare(0, head.size(), head) == 0) return true;
      if (subject == pattern.substr(0, pattern.size() - 2)) return true;
    }
    return false;
  }

  // --------------------------------------------------------------- queues

  void answer_pop(const PopWaiter& w, Value data) {
    auto it = conns.find(w.conn_id);
    if (it == conns.end() || it->second->dead) return;
    Value req = Value::mapv();
    req.set("i", Value::integer(w.msg_id));
    reply(*it->second, req, std::move(data));
  }

  // -------------------------------------------------------------- dispatch

  void dispatch(Conn& c, const Value& m) {
    const Value& opv = m.get("op");
    if (!opv.is_str()) {
      reply_err(c, m, "missing op");
      return;
    }
    const std::string& op = opv.s;
    try {
      if (op == "ping") {
        reply(c, m, Value::str("pong"));
      } else if (op == "kv_put") {
        reply(c, m, Value::integer(kv_set(m.get("key").as_str(),
                                          m.get("value").as_bytes(),
                                          m.get("lease").is_nil() ? 0 : m.get("lease").as_int())));
      } else if (op == "kv_get") {
        auto it = kv.find(m.get("key").as_str());
        if (it == kv.end()) {
          reply(c, m, Value::nil());
        } else {
          Value v = Value::mapv();
          v.set("value", Value::bin(it->second.value));
          v.set("rev", Value::integer(it->second.rev));
          v.set("lease", Value::integer(it->second.lease));
          reply(c, m, std::move(v));
        }
      } else if (op == "kv_get_prefix") {
        reply(c, m, kv_get_prefix(m.get("prefix").as_str()));
      } else if (op == "kv_del") {
        const std::string key = m.get("key").as_str();
        if (m.get("prefix").truthy()) {
          std::vector<std::string> keys;
          for (auto it = kv.lower_bound(key); it != kv.end(); ++it) {
            if (it->first.compare(0, key.size(), key) != 0) break;
            keys.push_back(it->first);
          }
          int64_t n = 0;
          for (const auto& k : keys) n += kv_delete(k) ? 1 : 0;
          reply(c, m, Value::integer(n));
        } else {
          reply(c, m, Value::integer(kv_delete(key) ? 1 : 0));
        }
      } else if (op == "kv_create") {
        const std::string key = m.get("key").as_str();
        if (kv.count(key)) {
          reply(c, m, Value::boolean(false));
        } else {
          kv_set(key, m.get("value").as_bytes(),
                 m.get("lease").is_nil() ? 0 : m.get("lease").as_int());
          reply(c, m, Value::boolean(true));
        }
      } else if (op == "kv_create_or_validate") {
        const std::string key = m.get("key").as_str();
        auto it = kv.find(key);
        if (it == kv.end()) {
          kv_set(key, m.get("value").as_bytes(),
                 m.get("lease").is_nil() ? 0 : m.get("lease").as_int());
          reply(c, m, Value::boolean(true));
        } else {
          reply(c, m, Value::boolean(it->second.value == m.get("value").as_bytes()));
        }
      } else if (op == "watch_prefix") {
        int64_t wid = m.get("watch_id").as_int();
        const std::string prefix = m.get("prefix").as_str();
        watches[{c.id, wid}] = prefix;
        c.watches.insert(wid);
        Value r = Value::mapv();
        r.set("watch_id", Value::integer(wid));
        r.set("snapshot", kv_get_prefix(prefix));
        r.set("rev", Value::integer(revision));
        reply(c, m, std::move(r));
      } else if (op == "watch_cancel") {
        int64_t wid = m.get("watch_id").as_int();
        watches.erase({c.id, wid});
        c.watches.erase(wid);
        reply(c, m, Value::boolean(true));
      } else if (op == "lease_grant") {
        double ttl = m.get("ttl").is_nil() ? 10.0 : m.get("ttl").as_double();
        int64_t id = next_lease_id++;
        leases[id] = Lease{ttl, now_mono() + ttl, {}};
        Value r = Value::mapv();
        r.set("lease_id", Value::integer(id));
        r.set("ttl", Value::real(ttl));
        reply(c, m, std::move(r));
      } else if (op == "lease_keepalive") {
        auto it = leases.find(m.get("lease_id").as_int());
        if (it == leases.end()) {
          reply(c, m, Value::boolean(false));
        } else {
          it->second.deadline = now_mono() + it->second.ttl;
          reply(c, m, Value::boolean(true));
        }
      } else if (op == "lease_revoke") {
        reply(c, m, Value::boolean(revoke_lease(m.get("lease_id").as_int())));
      } else if (op == "lease_is_valid") {
        reply(c, m, Value::boolean(leases.count(m.get("lease_id").as_int()) > 0));
      } else if (op == "subscribe") {
        int64_t sid = m.get("sub_id").as_int();
        subs[{c.id, sid}] = m.get("subject").as_str();
        c.subs.insert(sid);
        Value r = Value::mapv();
        r.set("sub_id", Value::integer(sid));
        reply(c, m, std::move(r));
      } else if (op == "unsubscribe") {
        int64_t sid = m.get("sub_id").as_int();
        subs.erase({c.id, sid});
        c.subs.erase(sid);
        reply(c, m, Value::boolean(true));
      } else if (op == "publish") {
        const std::string subject = m.get("subject").as_str();
        const Value& data = m.get("data");
        int64_t n = 0;
        for (const auto& s : subs) {
          if (subject_matches(s.second, subject)) {
            Value ev = Value::mapv();
            ev.set("subject", Value::str(subject));
            ev.set("data", data);
            push_to(s.first.first, s.first.second, std::move(ev));
            ++n;
          }
        }
        reply(c, m, Value::integer(n));
      } else if (op == "q_push") {
        const std::string name = m.get("name").as_str();
        // hand to the first waiter whose connection is still live —
        // dead-but-unreaped waiters must not eat the item (server.py
        // skips done futures the same way)
        bool delivered = false;
        auto wit = pop_waiters.find(name);
        if (wit != pop_waiters.end()) {
          auto& v = wit->second;
          while (!v.empty()) {
            PopWaiter w = v.front();
            v.erase(v.begin());
            auto cit = conns.find(w.conn_id);
            if (cit != conns.end() && !cit->second->dead) {
              answer_pop(w, m.get("data"));
              delivered = true;
              break;
            }
          }
          if (v.empty()) pop_waiters.erase(wit);
        }
        if (delivered) {
          reply(c, m, Value::integer(0));
        } else {
          auto& q = queues[name];
          q.push_back(m.get("data"));
          reply(c, m, Value::integer(static_cast<int64_t>(q.size())));
        }
      } else if (op == "q_pop") {
        const std::string name = m.get("name").as_str();
        auto qit = queues.find(name);
        if (qit != queues.end() && !qit->second.empty()) {
          Value data = std::move(qit->second.front());
          qit->second.pop_front();
          reply(c, m, std::move(data));
        } else if (!m.get("block").truthy()) {
          reply(c, m, Value::nil());
        } else {
          PopWaiter w;
          w.conn_id = c.id;
          w.msg_id = m.get("i").as_int();
          const Value& to = m.get("timeout");
          if (!to.is_nil()) {
            w.has_deadline = true;
            w.deadline = now_mono() + to.as_double();
          }
          pop_waiters[name].push_back(w);
        }
      } else if (op == "q_len") {
        auto qit = queues.find(m.get("name").as_str());
        reply(c, m, Value::integer(
            qit == queues.end() ? 0 : static_cast<int64_t>(qit->second.size())));
      } else if (op == "obj_put") {
        objects[m.get("bucket").as_str()][m.get("name").as_str()] = m.get("data");
        reply(c, m, Value::boolean(true));
      } else if (op == "obj_get") {
        auto bit = objects.find(m.get("bucket").as_str());
        if (bit == objects.end()) {
          reply(c, m, Value::nil());
        } else {
          auto oit = bit->second.find(m.get("name").as_str());
          reply(c, m, oit == bit->second.end() ? Value::nil() : oit->second);
        }
      } else if (op == "obj_del") {
        auto bit = objects.find(m.get("bucket").as_str());
        bool hit = false;
        if (bit != objects.end()) hit = bit->second.erase(m.get("name").as_str()) > 0;
        reply(c, m, Value::boolean(hit));
      } else if (op == "obj_list") {
        Value out = Value::array();
        auto bit = objects.find(m.get("bucket").as_str());
        if (bit != objects.end())
          for (const auto& o : bit->second) out.arr.push_back(Value::str(o.first));
        reply(c, m, std::move(out));
      } else if (op == "stats") {
        Value qs = Value::mapv();
        for (const auto& q : queues)
          qs.set(q.first, Value::integer(static_cast<int64_t>(q.second.size())));
        Value r = Value::mapv();
        r.set("keys", Value::integer(static_cast<int64_t>(kv.size())));
        r.set("leases", Value::integer(static_cast<int64_t>(leases.size())));
        r.set("conns", Value::integer(static_cast<int64_t>(conns.size())));
        r.set("watches", Value::integer(static_cast<int64_t>(watches.size())));
        r.set("subs", Value::integer(static_cast<int64_t>(subs.size())));
        r.set("queues", std::move(qs));
        r.set("revision", Value::integer(revision));
        reply(c, m, std::move(r));
      } else {
        reply_err(c, m, "unknown op '" + op + "'");
      }
    } catch (const std::exception& e) {
      reply_err(c, m, e.what());
    }
  }

  // ------------------------------------------------------------ connection

  void drop_conn(Conn& c) {
    c.dead = true;
    for (int64_t wid : c.watches) watches.erase({c.id, wid});
    for (int64_t sid : c.subs) subs.erase({c.id, sid});
    for (auto it = pop_waiters.begin(); it != pop_waiters.end();) {
      auto& v = it->second;
      v.erase(std::remove_if(v.begin(), v.end(),
                             [&](const PopWaiter& w) { return w.conn_id == c.id; }),
              v.end());
      it = v.empty() ? pop_waiters.erase(it) : std::next(it);
    }
    // leases are NOT revoked on disconnect: they expire by TTL, giving
    // workers a reconnect window (etcd semantics; server.py _drop_conn)
    if (c.fd >= 0) {
      close(c.fd);
      c.fd = -1;
    }
  }

  void handle_readable(Conn& c) {
    char chunk[65536];
    bool eof = false;
    for (;;) {
      ssize_t n = ::read(c.fd, chunk, sizeof(chunk));
      if (n > 0) {
        c.rbuf.append(chunk, static_cast<size_t>(n));
        continue;
      }
      if (n == 0) {  // clean EOF: still parse frames read in this batch —
        eof = true;  // fire-and-forget publishes may ride the same segment
        break;       // as the FIN (the C publisher's shutdown pattern)
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      c.dead = true;
      return;
    }
    // parse complete frames
    for (;;) {
      size_t avail = c.rbuf.size() - c.roff;
      if (avail < 4) break;
      const uint8_t* p = reinterpret_cast<const uint8_t*>(c.rbuf.data()) + c.roff;
      uint32_t len = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                     (uint32_t(p[2]) << 8) | uint32_t(p[3]);
      if (len > kMaxFrame) {
        c.dead = true;  // oversized frame: drop conn (codec.py behavior)
        return;
      }
      if (avail < 4 + static_cast<size_t>(len)) break;
      try {
        Value m = msgpack::unpack(p + 4, len);
        c.roff += 4 + len;
        dispatch(c, m);
      } catch (const std::exception&) {
        c.dead = true;  // malformed frame
        return;
      }
      if (c.dead) return;
    }
    if (c.roff > 0 && (c.roff == c.rbuf.size() || c.roff > (1u << 20))) {
      c.rbuf.erase(0, c.roff);
      c.roff = 0;
    }
    if (eof) {
      handle_writable(c);  // best-effort flush of any replies
      c.dead = true;
    }
  }

  void handle_writable(Conn& c) {
    while (c.woff < c.wbuf.size()) {
      ssize_t n = ::write(c.fd, c.wbuf.data() + c.woff, c.wbuf.size() - c.woff);
      if (n > 0) {
        c.woff += static_cast<size_t>(n);
        continue;
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      c.dead = true;
      return;
    }
    if (c.woff == c.wbuf.size()) {
      c.wbuf.clear();
      c.woff = 0;
    } else if (c.woff > (1u << 20)) {
      c.wbuf.erase(0, c.woff);
      c.woff = 0;
    }
  }

  void sweep_timers() {
    double now = now_mono();
    if (now >= next_lease_sweep) {
      next_lease_sweep = now + kLeaseTick;
      std::vector<int64_t> expired;
      for (const auto& l : leases)
        if (l.second.deadline < now) expired.push_back(l.first);
      for (int64_t id : expired) revoke_lease(id);
    }
    for (auto it = pop_waiters.begin(); it != pop_waiters.end();) {
      auto& v = it->second;
      for (auto w = v.begin(); w != v.end();) {
        if (w->has_deadline && w->deadline <= now) {
          answer_pop(*w, Value::nil());
          w = v.erase(w);
        } else {
          ++w;
        }
      }
      it = v.empty() ? pop_waiters.erase(it) : std::next(it);
    }
  }

  int poll_timeout_ms() const {
    double now = now_mono();
    double next = next_lease_sweep;
    for (const auto& q : pop_waiters)
      for (const auto& w : q.second)
        if (w.has_deadline && w.deadline < next) next = w.deadline;
    double dt = next - now;
    if (dt < 0.0) dt = 0.0;
    if (dt > 1.0) dt = 1.0;
    return static_cast<int>(dt * 1000.0) + 1;
  }

  void run() {
    std::vector<pollfd> pfds;
    std::vector<Conn*> pconns;
    for (;;) {
      pfds.clear();
      pconns.clear();
      pfds.push_back({listen_fd, POLLIN, 0});
      for (auto& kvp : conns) {
        Conn* c = kvp.second.get();
        short events = POLLIN;
        if (c->woff < c->wbuf.size()) events |= POLLOUT;
        pfds.push_back({c->fd, events, 0});
        pconns.push_back(c);
      }
      int rc = ::poll(pfds.data(), pfds.size(), poll_timeout_ms());
      if (rc < 0 && errno != EINTR) break;
      sweep_timers();
      if (rc > 0) {
        if (pfds[0].revents & POLLIN) accept_new();
        for (size_t k = 0; k < pconns.size(); ++k) {
          Conn* c = pconns[k];
          short re = pfds[k + 1].revents;
          if (re & (POLLERR | POLLHUP | POLLNVAL)) c->dead = true;
          if (!c->dead && (re & POLLIN)) handle_readable(*c);
          if (!c->dead && (re & POLLOUT)) handle_writable(*c);
        }
      }
      // flush anything dispatch produced on conns that weren't POLLOUT-armed
      for (auto& kvp : conns) {
        Conn* c = kvp.second.get();
        if (!c->dead && c->woff < c->wbuf.size()) handle_writable(*c);
      }
      // reap dead conns
      std::vector<int> dead;
      for (auto& kvp : conns)
        if (kvp.second->dead) dead.push_back(kvp.first);
      for (int id : dead) {
        drop_conn(*conns[id]);
        conns.erase(id);
      }
    }
  }

  void accept_new() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (errno == EMFILE || errno == ENFILE) {
          // fd exhaustion: the pending conn stays in the backlog and
          // poll() would spin on POLLIN — back off briefly instead
          struct timespec ts{0, 50 * 1000 * 1000};
          nanosleep(&ts, nullptr);
        }
        break;
      }
      set_nonblock(fd);
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto c = std::make_unique<Conn>();
      c->fd = fd;
      c->id = next_conn_id++;
      conns[c->id] = std::move(c);
    }
  }
};

int main(int argc, char** argv) {
  const char* host = "127.0.0.1";
  int port = 0;
  for (int k = 1; k < argc; ++k) {
    if (!strcmp(argv[k], "--host") && k + 1 < argc) host = argv[++k];
    else if (!strcmp(argv[k], "--port") && k + 1 < argc) port = atoi(argv[++k]);
    else {
      fprintf(stderr, "usage: dynamo-hubd [--host H] [--port P]\n");
      return 2;
    }
  }
  signal(SIGPIPE, SIG_IGN);
  Hub hub;
  if (!hub.listen(host, static_cast<uint16_t>(port))) {
    fprintf(stderr, "dynamo-hubd: bind %s:%d failed: %s\n", host, port,
            strerror(errno));
    return 1;
  }
  printf("LISTENING %u\n", hub.port);
  fflush(stdout);
  hub.run();
  return 0;
}
