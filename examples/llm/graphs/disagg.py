"""Disaggregated serving graph: frontend -> decode worker, with prefill
workers competing on the hub queue (reference:
examples/llm/graphs/disagg.py:16-21).

    python -m dynamo_tpu.sdk serve examples/llm/graphs/disagg.py:Frontend \
        -f examples/llm/configs/disagg.yaml
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from components import Frontend, PrefillWorker, Worker  # noqa: F401

from dynamo_tpu.sdk import depends

# the prefill pool talks to the decode worker through the hub queue, not a
# call edge — the depends() below only pulls PrefillWorker into the served
# graph (reference disagg.py links it into the chain for the same reason)
Worker.prefill = depends(PrefillWorker)
