"""Aggregated serving graph: HTTP frontend -> native engine worker
(reference: examples/llm/graphs/agg.py:16-18).

    python -m dynamo_tpu.sdk serve examples/llm/graphs/agg.py:Frontend \
        -f examples/llm/configs/agg.yaml
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from components import Frontend, Worker  # noqa: F401  (graph edge: Frontend -> Worker)
