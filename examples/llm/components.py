"""LLM serving components for the example graphs (reference:
examples/llm/components/{frontend,processor,worker,prefill_worker}.py —
there BentoML @service classes wrapping vLLM; here SDK @service classes
wrapping the native JaxEngine stack).

Every component reads its knobs from the graph config YAML
(`ServiceConfig`, exposed as `self.dynamo_context["config"]`):

Frontend:     port, model-name
Worker:       model-path, model-name, page-size, max-batch-size,
              max-model-len, attn-backend, disagg ("agg" | "decode"),
              max-local-prefill-length
PrefillWorker: model-path, model-name (+ engine knobs above)
"""

from __future__ import annotations

import asyncio

from dynamo_tpu.sdk import async_on_start, depends, service

NAMESPACE = "dynamo"


def _engine_kwargs(cfg: dict) -> dict:
    kw = {}
    if cfg.get("page-size"):
        kw["page_size"] = int(cfg["page-size"])
    if cfg.get("max-batch-size"):
        kw["max_batch_size"] = int(cfg["max-batch-size"])
    if cfg.get("max-model-len"):
        kw["max_model_len"] = int(cfg["max-model-len"])
    if cfg.get("attn-backend"):
        kw["attn_backend"] = cfg["attn-backend"]
    if cfg.get("tensor-parallel-size"):
        from dynamo_tpu.parallel.mesh import MeshConfig

        kw["mesh"] = MeshConfig(tp=int(cfg["tensor-parallel-size"]))
    return kw


@service(name="PrefillWorker", namespace=NAMESPACE)
class PrefillWorker:
    """Competes on the hub prefill queue; computes prompt KV and ships it
    to the requesting decode worker (reference:
    examples/llm/components/prefill_worker.py)."""

    def __init__(self):
        self.cfg = self.dynamo_context["config"]

    @async_on_start
    async def start(self):
        from dynamo_tpu.llm.disagg import PrefillHandler
        from dynamo_tpu.llm.local_model import LocalModel

        drt = self.dynamo_context["runtime"]
        lm = LocalModel.prepare(
            self.cfg["model-path"], name=self.cfg.get("model-name")
        )
        engine = lm.build_engine(**_engine_kwargs(self.cfg))
        PrefillHandler(drt, engine, NAMESPACE, "Worker").start()


@service(name="Worker", namespace=NAMESPACE)
class Worker:
    """Decode/aggregated worker: native JaxEngine registered on
    dyn://dynamo.Worker.generate with KV metrics + events published
    (reference: examples/llm/components/worker.py VllmWorker)."""

    def __init__(self):
        self.cfg = self.dynamo_context["config"]

    @async_on_start
    async def start(self):
        from dynamo_tpu.llm.http.discovery import register_llm
        from dynamo_tpu.llm.kv_router import KvEventPublisher, KvMetricsPublisher
        from dynamo_tpu.llm.local_model import LocalModel

        drt = self.dynamo_context["runtime"]
        lm = LocalModel.prepare(
            self.cfg["model-path"], name=self.cfg.get("model-name")
        )
        engine = lm.build_engine(**_engine_kwargs(self.cfg))
        lm.card.kv_cache_block_size = engine.page_size
        serving = engine
        if self.cfg.get("disagg") == "decode":
            from dynamo_tpu.llm.disagg import (
                DisaggConfig,
                DisaggDecodeWorker,
                DisaggRouter,
            )

            serving = DisaggDecodeWorker(
                drt, engine, NAMESPACE, "Worker",
                router=DisaggRouter(
                    drt, model=lm.card.display_name,
                    config=DisaggConfig(
                        max_local_prefill_length=int(
                            self.cfg.get("max-local-prefill-length", 128)
                        )
                    ),
                ),
            )
            await serving.attach()
        metrics = KvMetricsPublisher.for_engine(engine)
        component = drt.namespace(NAMESPACE).component("Worker")
        KvEventPublisher(component, drt.worker_id).attach(engine).start()
        await register_llm(
            drt, serving, lm.card, f"dyn://{NAMESPACE}.Worker.generate",
            stats_handler=metrics.stats_handler,
        )


@service(name="Frontend", namespace=NAMESPACE)
class Frontend:
    """OpenAI HTTP ingress: watches the hub for registered models and
    builds preprocess->route->detokenize pipelines per model (reference:
    examples/llm/components/frontend.py + processor.py — the processor
    stage is folded into the pipeline here)."""

    worker = depends(Worker)

    def __init__(self):
        self.cfg = self.dynamo_context["config"]

    @async_on_start
    async def start(self):
        from dynamo_tpu.llm.http.discovery import ModelWatcher
        from dynamo_tpu.llm.http.service import HttpService

        drt = self.dynamo_context["runtime"]
        self.svc = HttpService()
        self.watcher = ModelWatcher(
            drt, self.svc.manager,
            router_mode=self.cfg.get("router", "round_robin"),
        )
        await self.watcher.start()
        await self.svc.start("0.0.0.0", int(self.cfg.get("port", 8080)))
        # hold the HTTP server open for the worker process lifetime
        asyncio.get_running_loop().create_task(asyncio.Event().wait())
