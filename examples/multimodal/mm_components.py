"""Multimodal serving components (reference: examples/multimodal — an
encode worker computes image embeddings, the LLM worker injects them as
prompt embeddings and prefills/decodes as usual; stages scale
independently).

Flow (reference README's figure, hub edition):

    HTTP -> MMWorker --image--> EncodeWorker
                    <--[T_img, D] embeddings--
            MMWorker: placeholder tokens + prompt_embeds -> JaxEngine
"""

from __future__ import annotations

from dynamo_tpu.sdk import async_on_start, depends, endpoint, service

NAMESPACE = "mm"
PLACEHOLDER_TOKEN = 3  # expands to num_patches positions in the prompt


@service(name="EncodeWorker", namespace=NAMESPACE)
class EncodeWorker:
    """Vision encode stage: image array -> LLM-space patch embeddings."""

    def __init__(self):
        cfg = self.dynamo_context["config"]
        import jax

        from dynamo_tpu.models.vision import VisionConfig, init_vision_params

        self.vcfg = VisionConfig(
            out_size=int(cfg.get("llm-hidden-size", 2048)),
            image_size=int(cfg.get("image-size", 64)),
        )
        self.params = init_vision_params(
            self.vcfg, jax.random.PRNGKey(int(cfg.get("seed", 0)))
        )

    @endpoint()
    async def encode(self, request):
        import jax.numpy as jnp
        import numpy as np

        from dynamo_tpu.models.vision import encode

        image = np.asarray(request.payload["image"], np.float32)

        async def stream():
            emb = encode(self.params, self.vcfg, jnp.asarray(image[None]))
            yield {"embeddings": np.asarray(emb[0]).tolist()}

        return stream()


@service(name="MMWorker", namespace=NAMESPACE)
class MMWorker:
    """LLM stage: fetches image embeddings from the encode pool, injects
    them as prompt embeddings, serves through the native engine."""

    encoder = depends(EncodeWorker, endpoint="encode")

    def __init__(self):
        self.cfg = self.dynamo_context["config"]

    @async_on_start
    async def start(self):
        from dynamo_tpu.llm.local_model import LocalModel

        lm = LocalModel.prepare(
            self.cfg["model-path"], name=self.cfg.get("model-name")
        )
        kw = {}
        for yaml_key, attr in (
            ("page-size", "page_size"), ("max-batch-size", "max_batch_size"),
            ("max-model-len", "max_model_len"),
        ):
            if self.cfg.get(yaml_key):
                kw[attr] = int(self.cfg[yaml_key])
        self.engine = lm.build_engine(**kw)
        await self.encoder.wait_for_instances()

    @endpoint()
    async def generate(self, request):
        """payload: PreprocessedRequest dict + optional 'image' [H, W, 3];
        the image expands into placeholder positions at embeds_offset."""
        from dynamo_tpu.llm.protocols.common import PreprocessedRequest

        payload = dict(request.payload)
        image = payload.pop("image", None)
        pre = PreprocessedRequest.from_dict(payload)
        if image is not None:
            emb = None
            async for frame in await self.encoder.generate({"image": image}):
                emb = frame.get("embeddings")
            if emb is None:
                raise RuntimeError("encode worker returned no embeddings")
            if emb and len(emb[0]) != self.engine.model_cfg.hidden_size:
                raise RuntimeError(
                    f"encoder llm-hidden-size {len(emb[0])} != model hidden "
                    f"size {self.engine.model_cfg.hidden_size} — fix "
                    "EncodeWorker.llm-hidden-size in the graph config"
                )
            n_patches = len(emb)
            offset = len(pre.token_ids)
            pre.token_ids = (
                list(pre.token_ids) + [PLACEHOLDER_TOKEN] * n_patches
            )
            pre.prompt_embeds = emb
            pre.embeds_offset = offset
        return await self.engine.generate(request.map(pre.to_dict()))
