"""Multimodal aggregated graph: LLM worker + independently scalable
encode pool (reference: examples/multimodal/graphs/agg.py).

    python -m dynamo_tpu.sdk serve examples/multimodal/graphs/agg.py:MMWorker \
        -f examples/multimodal/configs/agg.yaml
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mm_components import EncodeWorker, MMWorker  # noqa: F401
